"""Matchmaker MultiPaxos: MultiPaxos with live acceptor reconfiguration.

Reference behavior: matchmakermultipaxos/ (~4,900 LoC Scala: Leader,
Matchmaker.scala:79-700, Reconfigurer.scala:98-720, Acceptor, Replica;
SURVEY.md section 2.2). Every round has its own quorum system over an
arbitrary acceptor set, registered with 2f+1 matchmakers:

  * to start round r, the leader matchmakes: MatchRequest(r, config) to
    the matchmakers of the current matchmaker epoch; f+1 MatchReplies
    return all prior-round configurations; phase 1 reads a read quorum
    of every prior configuration (for the whole log suffix); phase 2
    writes through the new round's own configuration -- the per-round
    quorum-systems shape that ops/quorum.py's MultiConfigQuorumChecker
    batches on device;
  * a Reconfigurer drives acceptor-set changes mid-stream by handing
    the leader a new configuration, which the leader adopts in its next
    round;
  * the matchmakers themselves are reconfigurable: epochs of 2f+1
    logical matchmakers, changed via the reference's
    Stop -> StopAck -> Bootstrap -> BootstrapAck -> MatchPhase1a/1b ->
    MatchPhase2a/2b -> MatchChosen protocol (Matchmaker.scala:462-662,
    Reconfigurer.scala:283-720). Stopped epochs bounce leaders to the
    new epoch via Stopped messages (Leader.scala:2212-2279);
  * GarbageCollect prunes matchmaker configurations below the leader's
    round once phase 1 has read them (Matchmaker.scala:400-460);
  * Die messages support chaos testing of matchmakers
    (Matchmaker.scala:664).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

import numpy as np

from frankenpaxos_tpu.quorums import (
    quorum_system_from_dict,
    quorum_system_to_dict,
    QuorumSystem,
    SimpleMajority,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap


@dataclasses.dataclass(frozen=True)
class MatchmakerMultiPaxosConfig:
    f: int
    leader_addresses: tuple
    matchmaker_addresses: tuple
    reconfigurer_addresses: tuple
    acceptor_addresses: tuple
    replica_addresses: tuple

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.matchmaker_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 matchmakers")
        if len(self.reconfigurer_addresses) < 1:
            raise ValueError("need >= 1 reconfigurer")
        if len(self.acceptor_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


@dataclasses.dataclass(frozen=True)
class MatchmakerConfiguration:
    """An epoch of 2f+1 logical matchmakers (MatchmakerConfiguration in
    the reference's proto; epoch 0 is matchmakers 0..2f)."""

    epoch: int
    reconfigurer_index: int
    matchmaker_indices: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
Value = Union[Command, Noop]


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    result: bytes


# --- leader <-> matchmaker ------------------------------------------------
@dataclasses.dataclass(frozen=True)
class MatchRequest:
    matchmaker_configuration: MatchmakerConfiguration
    round: int
    quorum_system: dict


@dataclasses.dataclass(frozen=True)
class MatchReply:
    epoch: int
    round: int
    matchmaker_index: int
    gc_watermark: int
    configurations: tuple[tuple[int, dict], ...]  # (round, quorum system)


@dataclasses.dataclass(frozen=True)
class MatchmakerNack:
    round: int


@dataclasses.dataclass(frozen=True)
class Stopped:
    """The contacted matchmaker epoch has stopped; move to the next
    epoch (Matchmaker.scala:366-371)."""

    epoch: int


@dataclasses.dataclass(frozen=True)
class GarbageCollect:
    """Prune matchmaker configurations below ``gc_watermark`` once
    phase 1 has read everything it needs (Matchmaker.scala:400-460)."""

    matchmaker_configuration: MatchmakerConfiguration
    gc_watermark: int


@dataclasses.dataclass(frozen=True)
class GarbageCollectAck:
    epoch: int
    matchmaker_index: int
    gc_watermark: int


# --- reconfigurer <-> matchmaker (matchmaker self-reconfiguration) --------
@dataclasses.dataclass(frozen=True)
class Stop:
    matchmaker_configuration: MatchmakerConfiguration


@dataclasses.dataclass(frozen=True)
class StopAck:
    matchmaker_index: int
    epoch: int
    gc_watermark: int
    configurations: tuple[tuple[int, dict], ...]


@dataclasses.dataclass(frozen=True)
class Bootstrap:
    epoch: int
    reconfigurer_index: int
    gc_watermark: int
    configurations: tuple[tuple[int, dict], ...]


@dataclasses.dataclass(frozen=True)
class BootstrapAck:
    matchmaker_index: int
    epoch: int


@dataclasses.dataclass(frozen=True)
class MatchPhase1a:
    matchmaker_configuration: MatchmakerConfiguration
    round: int


@dataclasses.dataclass(frozen=True)
class MatchPhase1b:
    epoch: int
    round: int
    matchmaker_index: int
    vote_round: int
    vote_value: Optional[MatchmakerConfiguration]


@dataclasses.dataclass(frozen=True)
class MatchPhase2a:
    matchmaker_configuration: MatchmakerConfiguration
    round: int
    value: MatchmakerConfiguration


@dataclasses.dataclass(frozen=True)
class MatchPhase2b:
    epoch: int
    round: int
    matchmaker_index: int


@dataclasses.dataclass(frozen=True)
class MatchChosen:
    value: MatchmakerConfiguration


@dataclasses.dataclass(frozen=True)
class MatchNack:
    epoch: int
    round: int


@dataclasses.dataclass(frozen=True)
class ReconfigureMatchmakers:
    """Ask a reconfigurer to replace the matchmakers of
    ``matchmaker_configuration`` with ``new_matchmaker_indices``
    (Reconfigure in Reconfigurer.scala:357-404)."""

    matchmaker_configuration: MatchmakerConfiguration
    new_matchmaker_indices: tuple[int, ...]


# --- leader <-> acceptor --------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    chosen_watermark: int


@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    vote_round: int
    vote_value: Value


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    acceptor_index: int
    info: tuple[Phase1bSlotInfo, ...]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    value: Value


@dataclasses.dataclass(frozen=True)
class Phase2b:
    slot: int
    round: int
    acceptor_index: int


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: Value


@dataclasses.dataclass(frozen=True)
class AcceptorNack:
    round: int


@dataclasses.dataclass(frozen=True)
class Reconfigure:
    quorum_system: dict


@dataclasses.dataclass(frozen=True)
class Die:
    """Chaos: kill a matchmaker (Matchmaker.scala:664)."""


# --- leader states --------------------------------------------------------
@dataclasses.dataclass
class _Matchmaking:
    quorum_system: QuorumSystem
    matchmaker_configuration: MatchmakerConfiguration
    match_replies: dict[int, MatchReply]
    pending_batches: list[ClientRequest]


@dataclasses.dataclass
class _WaitingForNewMatchmakers:
    """The epoch we were matchmaking in stopped; a reconfigurer is
    finding us new matchmakers (Leader.scala:2229-2251)."""

    quorum_system: QuorumSystem
    pending_batches: list[ClientRequest]
    resend: object


@dataclasses.dataclass
class _Phase1:
    quorum_system: QuorumSystem
    previous: dict[int, QuorumSystem]
    pending_rounds: set[int]
    phase1bs: dict[int, Phase1b]
    pending_batches: list[ClientRequest]
    # quorum_backend="tpu": (sorted prior rounds, MultiConfigQuorumChecker)
    # evaluating "responders cover a read quorum" for every prior
    # configuration as one padded [K, G, N] device batch.
    checker: Optional[tuple] = None


@dataclasses.dataclass
class _Phase2:
    quorum_system: QuorumSystem
    pending_values: dict[int, Value]
    phase2bs: dict[int, set[int]]


def initial_matchmaker_configuration(f: int) -> MatchmakerConfiguration:
    return MatchmakerConfiguration(
        epoch=0, reconfigurer_index=-1,
        matchmaker_indices=tuple(range(2 * f + 1)))


class MMPLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 seed: int = 0, quorum_backend: str = "dict"):
        super().__init__(address, transport, logger)
        config.check_valid()
        if quorum_backend not in ("dict", "tpu"):
            raise ValueError(f"unknown quorum backend {quorum_backend!r}")
        self.config = config
        self.quorum_backend = quorum_backend
        self.rng = random.Random(seed)
        self.index = list(config.leader_addresses).index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = -1
        self.next_slot = 0
        self.chosen_watermark = 0
        self.log: BufferMap = BufferMap()
        self.state: object = None  # Inactive
        # Deferred matchmaker GC: set when phase 1 completes, fired once
        # every slot phase 1 recovered has been chosen in our round
        # (the reference's WaitingForLargerChosenWatermark gc state,
        # Leader.scala:2140-2160). GCing any earlier can lose a chosen
        # value: the old configurations would be pruned before their
        # votes were re-written through the new round.
        self._gc_pending: Optional[tuple[MatchmakerConfiguration, int,
                                         int]] = None
        # Highest GC watermark a matchmaker has acked.
        self.gc_acked_watermark = -1
        # The latest matchmaker epoch this leader knows about
        # (Leader.scala:550-552).
        self.matchmaker_configuration = initial_matchmaker_configuration(
            config.f)
        # The configuration to adopt at the next matchmaking, set by the
        # reconfigurer.
        self.next_quorum_system: QuorumSystem = SimpleMajority(
            range(2 * config.f + 1))
        self.match_resend_period_s = 1.0
        self._match_resend_timer = None
        if self.index == 0:
            self._start_matchmaking(self.round)

    # --- matchmaking ------------------------------------------------------
    def _start_matchmaking(self, from_round: int) -> None:
        pending = []
        if isinstance(self.state,
                      (_Matchmaking, _Phase1, _WaitingForNewMatchmakers)):
            pending = self.state.pending_batches
        if from_round >= self.round:
            self.round = self.round_system.next_classic_round(self.index,
                                                              from_round)
        self._matchmake(self.round, self.next_quorum_system, pending)

    def _matchmake(self, round: int, quorum_system: QuorumSystem,
                   pending: list[ClientRequest]) -> None:
        """Send MatchRequests for ``round`` to the current matchmaker
        epoch (startMatchmaking, Leader.scala:905-935)."""
        self._gc_pending = None  # a new round supersedes any pending GC
        self.round = round
        self.state = _Matchmaking(quorum_system,
                                  self.matchmaker_configuration, {}, pending)
        self._send_match_requests()
        # Resend while still matchmaking: the initial MatchRequests can
        # race matchmaker startup or be dropped (resendMatchRequests,
        # Leader.scala:259-272). One reusable timer (created lazily once)
        # whose callback reads current state, so churny reconfigurations
        # don't allocate a timer per round.
        if self._match_resend_timer is None:
            def resend():
                if isinstance(self.state, _Matchmaking):
                    self._send_match_requests()
                    self._match_resend_timer.start()

            self._match_resend_timer = self.timer(
                "resendMatchRequests", self.match_resend_period_s, resend)
        self._match_resend_timer.stop()
        self._match_resend_timer.start()

    def _send_match_requests(self) -> None:
        state = self.state
        assert isinstance(state, _Matchmaking)
        request = MatchRequest(
            matchmaker_configuration=state.matchmaker_configuration,
            round=self.round,
            quorum_system=quorum_system_to_dict(state.quorum_system))
        for i in state.matchmaker_configuration.matchmaker_indices:
            self.send(self.config.matchmaker_addresses[i], request)

    def _acceptor(self, index: int) -> Address:
        return self.config.acceptor_addresses[index]

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, MatchReply):
            self._handle_match_reply(src, message)
        elif isinstance(message, (MatchmakerNack, AcceptorNack)):
            self._handle_nack(message.round)
        elif isinstance(message, Stopped):
            self._handle_stopped(src, message)
        elif isinstance(message, MatchChosen):
            self._handle_match_chosen(src, message)
        elif isinstance(message, GarbageCollectAck):
            self.gc_acked_watermark = max(self.gc_acked_watermark,
                                          message.gc_watermark)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Reconfigure):
            self._handle_reconfigure(src, message)
        elif isinstance(message, Chosen):
            self._learn(message.slot, message.value)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        if self.state is None:
            return
        if isinstance(self.state,
                      (_Matchmaking, _Phase1, _WaitingForNewMatchmakers)):
            self.state.pending_batches.append(request)
            return
        self._propose(request.command)

    def _propose(self, value: Value) -> None:
        state: _Phase2 = self.state
        slot = self.next_slot
        self.next_slot += 1
        state.pending_values[slot] = value
        state.phase2bs[slot] = set()
        phase2a = Phase2a(slot=slot, round=self.round, value=value)
        for i in state.quorum_system.random_write_quorum(self.rng):
            self.send(self._acceptor(i), phase2a)

    def _handle_match_reply(self, src: Address, reply: MatchReply) -> None:
        if not isinstance(self.state, _Matchmaking) \
                or reply.round != self.round:
            return
        state = self.state
        if reply.epoch != state.matchmaker_configuration.epoch:
            return
        state.match_replies[reply.matchmaker_index] = reply
        if len(state.match_replies) < self.config.f + 1:
            return
        # Rounds below the highest acked GC watermark were already fully
        # re-chosen through a later configuration; skip reading them even
        # if a laggard matchmaker still reports them.
        gc_watermark = max(r.gc_watermark
                           for r in state.match_replies.values())
        previous: dict[int, QuorumSystem] = {}
        for r in state.match_replies.values():
            for round, qs_dict in r.configurations:
                if round >= gc_watermark:
                    previous[round] = quorum_system_from_dict(qs_dict)
        pending_rounds = set(previous)
        if not pending_rounds:
            self.state = _Phase2(state.quorum_system, {}, {})
            for request in state.pending_batches:
                self._propose(request.command)
            return
        # Phase 1 over a read quorum of every prior configuration.
        targets: set[int] = set()
        for qs in previous.values():
            targets |= qs.random_read_quorum(self.rng)
        phase1a = Phase1a(round=self.round,
                          chosen_watermark=self.chosen_watermark)
        for i in targets:
            self.send(self._acceptor(i), phase1a)
        checker = None
        if self.quorum_backend == "tpu":
            # The quorum-matrix-reshape north star (SURVEY.md section 2.3):
            # each prior round's read predicate becomes one plane of a
            # padded [K, G, N] tensor; every Phase1b then re-checks all
            # prior configurations in a single device batch instead of the
            # per-round host loop (Leader.scala:1788-1999).
            from frankenpaxos_tpu.ops.quorum import MultiConfigQuorumChecker
            universe = tuple(range(len(self.config.acceptor_addresses)))
            rounds_sorted = sorted(previous)
            checker = (rounds_sorted, MultiConfigQuorumChecker(
                [previous[r].read_spec().reindexed(universe)
                 for r in rounds_sorted]))
        self.state = _Phase1(state.quorum_system, previous, pending_rounds,
                             {}, state.pending_batches, checker)

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1) \
                or phase1b.round != self.round:
            return
        state = self.state
        state.phase1bs[phase1b.acceptor_index] = phase1b
        responders = set(state.phase1bs)
        if state.checker is not None:
            rounds_sorted, checker = state.checker
            present = np.zeros(
                (len(rounds_sorted), len(self.config.acceptor_addresses)),
                dtype=np.uint8)
            present[:, sorted(responders)] = 1
            hits = checker.check_batch(
                present, np.arange(len(rounds_sorted), dtype=np.int32))
            for round, hit in zip(rounds_sorted, hits):
                if hit:
                    state.pending_rounds.discard(round)
        else:
            for round in list(state.pending_rounds):
                if state.previous[round].is_superset_of_read_quorum(
                        responders):
                    state.pending_rounds.discard(round)
        if state.pending_rounds:
            return
        max_slot = max((i.slot for p in state.phase1bs.values()
                        for i in p.info), default=-1)
        # Phase 1 done: matchmaker state below this round becomes
        # prunable -- but only once every recovered slot has been
        # re-chosen through THIS round's configuration, or a crash
        # between GC and phase 2 could lose a chosen value
        # (Leader.scala:2140-2160).
        self._gc_pending = (self.matchmaker_configuration, self.round,
                            max_slot)
        self._maybe_garbage_collect()
        phase2 = _Phase2(state.quorum_system, {}, {})
        pending = state.pending_batches
        self.state = phase2
        for slot in range(self.chosen_watermark, max_slot + 1):
            if self.log.get(slot) is not None:
                continue
            infos = [i for p in state.phase1bs.values() for i in p.info
                     if i.slot == slot]
            value = (max(infos, key=lambda i: i.vote_round).vote_value
                     if infos else NOOP)
            phase2.pending_values[slot] = value
            phase2.phase2bs[slot] = set()
            phase2a = Phase2a(slot=slot, round=self.round, value=value)
            for i in phase2.quorum_system.random_write_quorum(self.rng):
                self.send(self._acceptor(i), phase2a)
        self.next_slot = max(self.next_slot, max_slot + 1,
                             self.chosen_watermark)
        for request in pending:
            self._propose(request.command)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        if not isinstance(self.state, _Phase2) \
                or phase2b.round != self.round:
            return
        state = self.state
        voters = state.phase2bs.get(phase2b.slot)
        if voters is None:
            return
        voters.add(phase2b.acceptor_index)
        if not state.quorum_system.is_superset_of_write_quorum(voters):
            return
        value = state.pending_values.pop(phase2b.slot)
        del state.phase2bs[phase2b.slot]
        self._learn(phase2b.slot, value)
        for replica in self.config.replica_addresses:
            self.send(replica, Chosen(slot=phase2b.slot, value=value))
        for leader in self.config.leader_addresses:
            if leader != self.address:
                self.send(leader, Chosen(slot=phase2b.slot, value=value))

    def _learn(self, slot: int, value: Value) -> None:
        if self.log.get(slot) is None:
            self.log.put(slot, value)
        while self.log.get(self.chosen_watermark) is not None:
            self.chosen_watermark += 1
        self.next_slot = max(self.next_slot, self.chosen_watermark)
        self._maybe_garbage_collect()

    def _maybe_garbage_collect(self) -> None:
        if self._gc_pending is None:
            return
        mc, round, max_slot = self._gc_pending
        if self.chosen_watermark <= max_slot:
            return
        self._gc_pending = None
        gc = GarbageCollect(matchmaker_configuration=mc, gc_watermark=round)
        for i in mc.matchmaker_indices:
            self.send(self.config.matchmaker_addresses[i], gc)

    def _handle_nack(self, nack_round: int) -> None:
        if nack_round < self.round or self.state is None:
            return
        self._start_matchmaking(max(self.round, nack_round))

    def _handle_stopped(self, src: Address, stopped: Stopped) -> None:
        """Our matchmaker epoch stopped mid-matchmaking: ask a
        reconfigurer for the new epoch (Leader.scala:2229-2251)."""
        if not isinstance(self.state, _Matchmaking):
            return
        if stopped.epoch != self.state.matchmaker_configuration.epoch:
            return
        stale_configuration = self.state.matchmaker_configuration

        def send_reconfigure():
            # Re-sample each attempt: a sample that includes a dead
            # matchmaker can never bootstrap (the reconfigurer waits for
            # ALL 2f+1 BootstrapAcks), so retries must try new sets.
            request = ReconfigureMatchmakers(
                matchmaker_configuration=stale_configuration,
                new_matchmaker_indices=tuple(self.rng.sample(
                    range(len(self.config.matchmaker_addresses)),
                    2 * self.config.f + 1)))
            self.send(self.rng.choice(self.config.reconfigurer_addresses),
                      request)

        def resend():
            send_reconfigure()
            timer.start()

        send_reconfigure()
        timer = self.timer("resendReconfigure", 5.0, resend)
        timer.start()
        self.state = _WaitingForNewMatchmakers(
            self.state.quorum_system, self.state.pending_batches, timer)

    def _handle_match_chosen(self, src: Address,
                             chosen: MatchChosen) -> None:
        """Adopt a newer matchmaker epoch (Leader.scala:2281-2310)."""
        if chosen.value.epoch <= self.matchmaker_configuration.epoch:
            return
        self.matchmaker_configuration = chosen.value
        if isinstance(self.state, (_WaitingForNewMatchmakers, _Matchmaking)):
            if isinstance(self.state, _WaitingForNewMatchmakers):
                self.state.resend.stop()
            self._matchmake(self.round, self.state.quorum_system,
                            self.state.pending_batches)

    def _handle_reconfigure(self, src: Address,
                            reconfigure: Reconfigure) -> None:
        """Adopt a new acceptor configuration in our next round
        (the Reconfigurer's handoff)."""
        if self.state is None:
            return
        self.next_quorum_system = quorum_system_from_dict(
            reconfigure.quorum_system)
        self._start_matchmaking(self.round)


# --- matchmaker per-epoch states (Matchmaker.scala:128-166) ---------------
@dataclasses.dataclass
class _MatchmakerLog:
    gc_watermark: int
    configurations: dict[int, dict]  # round -> quorum system dict


@dataclasses.dataclass
class _Pending:
    """Bootstrapped for a new epoch but not yet told the epoch was
    chosen; one candidate log per proposing reconfigurer."""

    logs: dict[int, _MatchmakerLog]


@dataclasses.dataclass
class _Normal:
    log: _MatchmakerLog


@dataclasses.dataclass
class _HasStopped:
    log: _MatchmakerLog


@dataclasses.dataclass
class _MatchmakerAcceptorState:
    """Single-decree acceptor state for choosing the next epoch's
    configuration (Matchmaker.scala:154-166)."""

    round: int = -1
    vote_round: int = -1
    vote_value: Optional[MatchmakerConfiguration] = None


class MMPMatchmaker(Actor):
    """Stores per-round acceptor configurations, epoch by epoch;
    monotone; supports GC, the Stop/Bootstrap/MatchPhase1/2 epoch
    change, and Die (Matchmaker.scala:79-700)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.matchmaker_addresses).index(address)
        self.states: dict[int, object] = {}
        self.acceptor_states: dict[int, _MatchmakerAcceptorState] = {}
        if self.index < 2 * config.f + 1:
            self.states[0] = _Normal(_MatchmakerLog(0, {}))
            self.acceptor_states[0] = _MatchmakerAcceptorState()
        self.dead = False

    # Compatibility views over the newest epoch's log (used by tests
    # and the viz tooling).
    @property
    def configurations(self) -> dict[int, dict]:
        log = self._newest_log()
        return dict(log.configurations) if log else {}

    @property
    def gc_watermark(self) -> int:
        log = self._newest_log()
        return log.gc_watermark if log else 0

    def _newest_log(self) -> Optional[_MatchmakerLog]:
        for epoch in sorted(self.states, reverse=True):
            state = self.states[epoch]
            if isinstance(state, (_Normal, _HasStopped)):
                return state.log
        return None

    def _to_normal(self, epoch: int,
                   reconfigurer_index: int) -> Optional[_Normal]:
        """Resolve the state for ``epoch`` to Normal, promoting a
        Pending log from ``reconfigurer_index`` (the 'pretend we just
        learned we were chosen' path, Matchmaker.scala:296-312)."""
        state = self.states.get(epoch)
        if isinstance(state, _Pending):
            log = state.logs.get(reconfigurer_index)
            if log is None:
                self.logger.fatal(
                    f"matchmaker {self.index}: no pending log from "
                    f"reconfigurer {reconfigurer_index} in epoch {epoch}")
            state = _Normal(log)
            self.states[epoch] = state
        if isinstance(state, _Normal):
            return state
        return None

    def _to_stopped(self, epoch: int,
                    reconfigurer_index: int) -> _HasStopped:
        state = self.states.get(epoch)
        if isinstance(state, _Pending):
            log = state.logs.get(reconfigurer_index)
            if log is None:
                self.logger.fatal(
                    f"matchmaker {self.index}: no pending log from "
                    f"reconfigurer {reconfigurer_index} in epoch {epoch}")
            state = _HasStopped(log)
        elif isinstance(state, _Normal):
            state = _HasStopped(state.log)
        elif state is None:
            self.logger.fatal(
                f"matchmaker {self.index}: unknown epoch {epoch}")
        self.states[epoch] = state
        return state

    def receive(self, src: Address, message) -> None:
        if self.dead:
            return
        if isinstance(message, MatchRequest):
            self._handle_match_request(src, message)
        elif isinstance(message, GarbageCollect):
            self._handle_garbage_collect(src, message)
        elif isinstance(message, Stop):
            self._handle_stop(src, message)
        elif isinstance(message, Bootstrap):
            self._handle_bootstrap(src, message)
        elif isinstance(message, MatchPhase1a):
            self._handle_match_phase1a(src, message)
        elif isinstance(message, MatchPhase2a):
            self._handle_match_phase2a(src, message)
        elif isinstance(message, MatchChosen):
            self._handle_match_chosen(src, message)
        elif isinstance(message, Die):
            self.dead = True
        else:
            self.logger.fatal(f"unexpected matchmaker message {message!r}")

    def _handle_match_request(self, src: Address,
                              request: MatchRequest) -> None:
        mc = request.matchmaker_configuration
        if mc.epoch not in self.states:
            # Leaders only contact an epoch's matchmakers after every
            # one of them was bootstrapped (Matchmaker.scala:283-289).
            self.logger.fatal(
                f"matchmaker {self.index}: MatchRequest in unknown "
                f"epoch {mc.epoch}")
        normal = self._to_normal(mc.epoch, mc.reconfigurer_index)
        if normal is None:  # HasStopped: bounce to the next epoch.
            self.send(src, Stopped(epoch=mc.epoch))
            return
        log = normal.log
        if request.round < log.gc_watermark:
            self.send(src, MatchmakerNack(round=log.gc_watermark - 1))
            return
        if log.configurations and request.round <= max(log.configurations):
            self.send(src, MatchmakerNack(round=max(log.configurations)))
            return
        # dict(...) per entry: the outer tuple alone would embed the
        # LIVE quorum-system dicts -- SimTransport delivers by
        # reference, so any future in-place edit would time-travel to
        # the leader (the ALIAS1001 hazard class); copying at this
        # cold-path send closes the repo's one shallow-alias edge.
        self.send(src, MatchReply(
            epoch=mc.epoch, round=request.round,
            matchmaker_index=self.index,
            gc_watermark=log.gc_watermark,
            configurations=tuple(
                (r, dict(log.configurations[r]))
                for r in sorted(log.configurations)
                if r < request.round)))
        log.configurations[request.round] = request.quorum_system

    def _handle_garbage_collect(self, src: Address,
                                gc: GarbageCollect) -> None:
        mc = gc.matchmaker_configuration
        if mc.epoch not in self.states:
            return
        normal = self._to_normal(mc.epoch, mc.reconfigurer_index)
        if normal is None:
            self.send(src, Stopped(epoch=mc.epoch))
            return
        log = normal.log
        log.gc_watermark = max(log.gc_watermark, gc.gc_watermark)
        for round in [r for r in log.configurations
                      if r < log.gc_watermark]:
            del log.configurations[round]
        self.send(src, GarbageCollectAck(
            epoch=mc.epoch, matchmaker_index=self.index,
            gc_watermark=log.gc_watermark))

    def _handle_stop(self, src: Address, stop: Stop) -> None:
        mc = stop.matchmaker_configuration
        stopped = self._to_stopped(mc.epoch, mc.reconfigurer_index)
        # Copy the inner quorum-system dicts like _handle_match_request
        # does: tuple(items()) alone is a shallow freeze.
        self.send(src, StopAck(
            matchmaker_index=self.index, epoch=mc.epoch,
            gc_watermark=stopped.log.gc_watermark,
            configurations=tuple(
                (r, dict(qs)) for r, qs in sorted(
                    stopped.log.configurations.items()))))

    def _handle_bootstrap(self, src: Address, bootstrap: Bootstrap) -> None:
        log = _MatchmakerLog(bootstrap.gc_watermark,
                             dict(bootstrap.configurations))
        state = self.states.get(bootstrap.epoch)
        if state is None:
            self.states[bootstrap.epoch] = _Pending(
                {bootstrap.reconfigurer_index: log})
            self.acceptor_states[bootstrap.epoch] = \
                _MatchmakerAcceptorState()
        elif isinstance(state, _Pending):
            state.logs[bootstrap.reconfigurer_index] = log
        # Normal/HasStopped: state unchanged, but ack for liveness.
        self.send(src, BootstrapAck(matchmaker_index=self.index,
                                    epoch=bootstrap.epoch))

    def _handle_match_phase1a(self, src: Address,
                              phase1a: MatchPhase1a) -> None:
        mc = phase1a.matchmaker_configuration
        self._to_stopped(mc.epoch, mc.reconfigurer_index)
        acceptor = self.acceptor_states[mc.epoch]
        if phase1a.round < acceptor.round:
            self.send(src, MatchNack(epoch=mc.epoch, round=acceptor.round))
            return
        self.send(src, MatchPhase1b(
            epoch=mc.epoch, round=phase1a.round,
            matchmaker_index=self.index,
            vote_round=acceptor.vote_round,
            vote_value=acceptor.vote_value))
        acceptor.round = phase1a.round

    def _handle_match_phase2a(self, src: Address,
                              phase2a: MatchPhase2a) -> None:
        mc = phase2a.matchmaker_configuration
        self._to_stopped(mc.epoch, mc.reconfigurer_index)
        acceptor = self.acceptor_states[mc.epoch]
        if phase2a.round < acceptor.round:
            self.send(src, MatchNack(epoch=mc.epoch, round=acceptor.round))
            return
        self.send(src, MatchPhase2b(epoch=mc.epoch, round=phase2a.round,
                                    matchmaker_index=self.index))
        acceptor.round = phase2a.round
        acceptor.vote_round = phase2a.round
        acceptor.vote_value = phase2a.value

    def _handle_match_chosen(self, src: Address,
                             chosen: MatchChosen) -> None:
        epoch = chosen.value.epoch
        state = self.states.get(epoch)
        if isinstance(state, _Pending):
            log = state.logs.get(chosen.value.reconfigurer_index)
            if log is None:
                self.logger.fatal(
                    f"matchmaker {self.index}: MatchChosen from unknown "
                    f"reconfigurer {chosen.value.reconfigurer_index}")
            self.states[epoch] = _Normal(log)


# --- reconfigurer states (Reconfigurer.scala:118-178) ---------------------
@dataclasses.dataclass
class _Idle:
    configuration: MatchmakerConfiguration


@dataclasses.dataclass
class _Stopping:
    configuration: MatchmakerConfiguration
    new_configuration: MatchmakerConfiguration
    stop_acks: dict[int, StopAck]
    resend: object


@dataclasses.dataclass
class _Bootstrapping:
    configuration: MatchmakerConfiguration
    new_configuration: MatchmakerConfiguration
    bootstrap_acks: dict[int, BootstrapAck]
    resend: object


@dataclasses.dataclass
class _MatchPhase1:
    configuration: MatchmakerConfiguration
    new_configuration: MatchmakerConfiguration
    round: int
    phase1bs: dict[int, MatchPhase1b]
    resend: object


@dataclasses.dataclass
class _MatchPhase2:
    configuration: MatchmakerConfiguration
    new_configuration: MatchmakerConfiguration
    round: int
    phase2bs: dict[int, MatchPhase2b]
    resend: object


class MMPReconfigurer(Actor):
    """Drives acceptor-set changes (handed to the leaders, which
    matchmake them into their next round) and matchmaker-set changes
    (the reference's Stop -> Bootstrap -> MatchPhase1/2 -> MatchChosen
    protocol, Reconfigurer.scala:98-720)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 resend_period_s: float = 5.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.index = list(config.reconfigurer_addresses).index(address)
        self.round_system = ClassicRoundRobin(
            len(config.reconfigurer_addresses))
        self.state: object = _Idle(
            initial_matchmaker_configuration(config.f))

    # --- external API -----------------------------------------------------
    def reconfigure(self, quorum_system: QuorumSystem) -> None:
        """Change the *acceptor* set: hand the leaders a new quorum
        system for their next round."""
        message = Reconfigure(quorum_system_to_dict(quorum_system))
        for leader in self.config.leader_addresses:
            self.send(leader, message)

    def reconfigure_matchmakers(self, indices) -> None:
        """Change the *matchmaker* set to ``indices`` (2f+1 of them)."""
        if not isinstance(self.state, _Idle):
            self.logger.debug("reconfiguration already in progress")
            return
        self._stop_epoch(self.state.configuration, tuple(indices))

    # --- helpers ----------------------------------------------------------
    def _matchmaker(self, index: int) -> Address:
        return self.config.matchmaker_addresses[index]

    def _resend_timer(self, name: str, message, indices) -> object:
        def resend():
            for i in indices:
                self.send(self._matchmaker(i), message)
            timer.start()

        timer = self.timer(name, self.resend_period_s, resend)
        timer.start()
        return timer

    def _stop_epoch(self, configuration: MatchmakerConfiguration,
                    new_indices: tuple[int, ...]) -> None:
        stop = Stop(matchmaker_configuration=configuration)
        for i in configuration.matchmaker_indices:
            self.send(self._matchmaker(i), stop)
        self.state = _Stopping(
            configuration=configuration,
            new_configuration=MatchmakerConfiguration(
                epoch=configuration.epoch + 1,
                reconfigurer_index=self.index,
                matchmaker_indices=new_indices),
            stop_acks={},
            resend=self._resend_timer("resendStops", stop,
                                      configuration.matchmaker_indices))

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, Reconfigure):
            for leader in self.config.leader_addresses:
                self.send(leader, message)
        elif isinstance(message, ReconfigureMatchmakers):
            self._handle_reconfigure_matchmakers(src, message)
        elif isinstance(message, StopAck):
            self._handle_stop_ack(src, message)
        elif isinstance(message, BootstrapAck):
            self._handle_bootstrap_ack(src, message)
        elif isinstance(message, MatchPhase1b):
            self._handle_match_phase1b(src, message)
        elif isinstance(message, MatchPhase2b):
            self._handle_match_phase2b(src, message)
        elif isinstance(message, MatchChosen):
            self._handle_match_chosen(src, message)
        elif isinstance(message, MatchNack):
            self._handle_match_nack(src, message)
        else:
            self.logger.fatal(f"unexpected reconfigurer message {message!r}")

    def _handle_reconfigure_matchmakers(
            self, src: Address, request: ReconfigureMatchmakers) -> None:
        if not isinstance(self.state, _Idle):
            return
        if request.matchmaker_configuration.epoch < \
                self.state.configuration.epoch:
            # Stale: the requester is behind; tell it the current epoch.
            self.send(src, MatchChosen(value=self.state.configuration))
            return
        self._stop_epoch(request.matchmaker_configuration,
                         request.new_matchmaker_indices)

    def _handle_stop_ack(self, src: Address, ack: StopAck) -> None:
        if not isinstance(self.state, _Stopping) \
                or ack.epoch != self.state.configuration.epoch:
            return
        state = self.state
        state.stop_acks[ack.matchmaker_index] = ack
        if len(state.stop_acks) < self.config.f + 1:
            return
        state.resend.stop()
        # Union the stopped logs, trim garbage, bootstrap the new epoch
        # (Reconfigurer.scala:436-470).
        gc_watermark = max(a.gc_watermark for a in state.stop_acks.values())
        configurations: dict[int, dict] = {}
        for a in state.stop_acks.values():
            for round, qs in a.configurations:
                if round >= gc_watermark:
                    configurations[round] = qs
        bootstrap = Bootstrap(
            epoch=state.new_configuration.epoch,
            reconfigurer_index=self.index,
            gc_watermark=gc_watermark,
            configurations=tuple(sorted(configurations.items())))
        for i in state.new_configuration.matchmaker_indices:
            self.send(self._matchmaker(i), bootstrap)
        self.state = _Bootstrapping(
            configuration=state.configuration,
            new_configuration=state.new_configuration,
            bootstrap_acks={},
            resend=self._resend_timer(
                "resendBootstraps", bootstrap,
                state.new_configuration.matchmaker_indices))

    def _handle_bootstrap_ack(self, src: Address,
                              ack: BootstrapAck) -> None:
        if not isinstance(self.state, _Bootstrapping) \
                or ack.epoch != self.state.new_configuration.epoch:
            return
        state = self.state
        state.bootstrap_acks[ack.matchmaker_index] = ack
        # Wait for ALL new matchmakers (Reconfigurer.scala:489-492).
        if len(state.bootstrap_acks) < 2 * self.config.f + 1:
            return
        state.resend.stop()
        self._start_match_phase1(
            state.configuration, state.new_configuration,
            self.round_system.next_classic_round(self.index, -1))

    def _start_match_phase1(self, configuration: MatchmakerConfiguration,
                            new_configuration: MatchmakerConfiguration,
                            round: int) -> None:
        phase1a = MatchPhase1a(matchmaker_configuration=configuration,
                               round=round)
        for i in configuration.matchmaker_indices:
            self.send(self._matchmaker(i), phase1a)
        self.state = _MatchPhase1(
            configuration=configuration,
            new_configuration=new_configuration,
            round=round, phase1bs={},
            resend=self._resend_timer("resendMatchPhase1as", phase1a,
                                      configuration.matchmaker_indices))

    def _handle_match_phase1b(self, src: Address,
                              phase1b: MatchPhase1b) -> None:
        if not isinstance(self.state, _MatchPhase1) \
                or phase1b.epoch != self.state.configuration.epoch \
                or phase1b.round != self.state.round:
            return
        state = self.state
        state.phase1bs[phase1b.matchmaker_index] = phase1b
        if len(state.phase1bs) < self.config.f + 1:
            return
        state.resend.stop()
        # Safe value: highest vote-round vote, else our proposal.
        votes = [p for p in state.phase1bs.values()
                 if p.vote_value is not None]
        value = (max(votes, key=lambda p: p.vote_round).vote_value
                 if votes else state.new_configuration)
        phase2a = MatchPhase2a(
            matchmaker_configuration=state.configuration,
            round=state.round, value=value)
        for i in state.configuration.matchmaker_indices:
            self.send(self._matchmaker(i), phase2a)
        self.state = _MatchPhase2(
            configuration=state.configuration,
            new_configuration=value,
            round=state.round, phase2bs={},
            resend=self._resend_timer(
                "resendMatchPhase2as", phase2a,
                state.configuration.matchmaker_indices))

    def _handle_match_phase2b(self, src: Address,
                              phase2b: MatchPhase2b) -> None:
        if not isinstance(self.state, _MatchPhase2) \
                or phase2b.epoch != self.state.configuration.epoch \
                or phase2b.round != self.state.round:
            return
        state = self.state
        state.phase2bs[phase2b.matchmaker_index] = phase2b
        if len(state.phase2bs) < self.config.f + 1:
            return
        state.resend.stop()
        # Inform the new matchmakers, other reconfigurers, and leaders.
        chosen = MatchChosen(value=state.new_configuration)
        for leader in self.config.leader_addresses:
            self.send(leader, chosen)
        for reconfigurer in self.config.reconfigurer_addresses:
            if reconfigurer != self.address:
                self.send(reconfigurer, chosen)
        for i in state.new_configuration.matchmaker_indices:
            self.send(self._matchmaker(i), chosen)
        self.state = _Idle(configuration=state.new_configuration)

    def _handle_match_chosen(self, src: Address,
                             chosen: MatchChosen) -> None:
        epoch = self.state.configuration.epoch
        if chosen.value.epoch <= epoch:
            return
        if not isinstance(self.state, _Idle):
            self.state.resend.stop()
        self.state = _Idle(chosen.value)

    def _handle_match_nack(self, src: Address, nack: MatchNack) -> None:
        if not isinstance(self.state, (_MatchPhase1, _MatchPhase2)):
            return
        state = self.state
        if nack.epoch != state.configuration.epoch \
                or nack.round <= state.round:
            return
        state.resend.stop()
        self._start_match_phase1(
            state.configuration, state.new_configuration,
            self.round_system.next_classic_round(self.index, nack.round))


@dataclasses.dataclass
class _VoteState:
    vote_round: int
    vote_value: Value


class MMPAcceptor(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.votes: dict[int, _VoteState] = {}

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            if message.round < self.round:
                self.send(src, AcceptorNack(round=self.round))
                return
            self.round = message.round
            info = tuple(
                Phase1bSlotInfo(slot=slot, vote_round=state.vote_round,
                                vote_value=state.vote_value)
                for slot, state in sorted(self.votes.items())
                if slot >= message.chosen_watermark)
            self.send(src, Phase1b(round=message.round,
                                   acceptor_index=self.index, info=info))
        elif isinstance(message, Phase2a):
            if message.round < self.round:
                self.send(src, AcceptorNack(round=self.round))
                return
            self.round = message.round
            self.votes[message.slot] = _VoteState(message.round,
                                                  message.value)
            self.send(src, Phase2b(slot=message.slot, round=message.round,
                                   acceptor_index=self.index))
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")


class MMPReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.index = list(config.replica_addresses).index(address)
        self.log: BufferMap = BufferMap()
        self.executed_watermark = 0
        self.client_table: dict[tuple, tuple[int, bytes]] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, Chosen):
            self.logger.fatal(f"unexpected replica message {message!r}")
        if self.log.get(message.slot) is None:
            self.log.put(message.slot, message.value)
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return
            slot = self.executed_watermark
            self.executed_watermark += 1
            if isinstance(value, Noop):
                continue
            cid = value.command_id
            key = (cid.client_address, cid.client_pseudonym)
            cached = self.client_table.get(key)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(value.command)
                self.client_table[key] = (cid.client_id, result)
            if slot % len(self.config.replica_addresses) == self.index:
                self.send(cid.client_address,
                          ClientReply(command_id=cid, result=result))


@dataclasses.dataclass
class _PendingWrite:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class MMPClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _PendingWrite] = {}

    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, id), command))

        def send_it():
            for leader in self.config.leader_addresses:
                self.send(leader, request)

        def resend():
            send_it()
            timer.start()

        send_it()
        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _PendingWrite(id, command,
                                                callback or (lambda _: None),
                                                timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.command_id.client_pseudonym)
        if pending is None or pending.id != message.command_id.client_id:
            return
        pending.resend.stop()
        del self.pending[message.command_id.client_pseudonym]
        pending.callback(message.result)


# --- driver-based chaos workloads ------------------------------------------
# (jvm/.../matchmakermultipaxos/Driver.scala + DriverWorkload.proto: the
# scripted schedules behind the VLDB'20 matchmaker experiments --
# repeated acceptor reconfiguration, matchmaker epoch changes, leader
# failure, and the combined Chaos schedule.)


@dataclasses.dataclass(frozen=True)
class DriverDoNothing:
    pass


@dataclasses.dataclass(frozen=True)
class DriverRepeatedReconfiguration:
    """Every ``period_s`` (after ``delay_s``), reconfigure the acceptor
    set to a random 2f+1 subset (DriverWorkload.proto:14-18)."""

    delay_s: float
    period_s: float


@dataclasses.dataclass(frozen=True)
class DriverMatchmakerReconfiguration:
    """Warmup acceptor reconfigurations, then matchmaker epoch changes
    (DriverWorkload.proto:31-41)."""

    warmup_delay_s: float
    warmup_period_s: float
    warmup_num: int
    matchmaker_delay_s: float
    matchmaker_period_s: float
    matchmaker_num: int


@dataclasses.dataclass(frozen=True)
class DriverChaos:
    """The combined chaos schedule (DriverWorkload.proto:50-66):
    warmups, then a matchmaker failure and recovery-by-epoch-change,
    plus an acceptor-set failure and recovery."""

    warmup_delay_s: float
    warmup_period_s: float
    warmup_num: int
    matchmaker_failure_delay_s: float
    matchmaker_recover_delay_s: float
    acceptor_failure_delay_s: float
    acceptor_recover_delay_s: float


MMPDriverWorkload = Union[DriverDoNothing, DriverRepeatedReconfiguration,
                          DriverMatchmakerReconfiguration, DriverChaos]


class MMPDriver(Actor):
    """Executes a scripted chaos schedule against a MatchmakerMultiPaxos
    deployment (Driver.scala:30+): acceptor reconfigurations via the
    reconfigurer's Reconfigure broadcast, matchmaker epoch changes via
    ReconfigureMatchmakers, matchmaker deaths via Die."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerMultiPaxosConfig,
                 workload: MMPDriverWorkload, seed: int = 0):
        super().__init__(address, transport, logger)
        self.config = config
        self.workload = workload
        self.rng = random.Random(seed)
        self.timers: list = []
        # Last known matchmaker epoch; refreshed by MatchChosen bounces
        # from the reconfigurer when this falls behind.
        self.matchmaker_configuration = initial_matchmaker_configuration(
            config.f)
        self._killed: set[int] = set()
        self._start()

    # --- actions -----------------------------------------------------------
    def reconfigure_acceptors(self) -> None:
        n = len(self.config.acceptor_addresses)
        subset = self.rng.sample(range(n), 2 * self.config.f + 1)
        message = Reconfigure(
            quorum_system_to_dict(SimpleMajority(subset)))
        for leader in self.config.leader_addresses:
            self.send(leader, message)

    def reconfigure_matchmakers(self) -> None:
        # Never bootstrap an epoch onto a matchmaker this driver killed:
        # Bootstrap needs every new matchmaker to ack.
        candidates = [i for i in range(len(
            self.config.matchmaker_addresses)) if i not in self._killed]
        needed = 2 * self.config.f + 1
        if len(candidates) < needed:
            self.logger.warn(
                f"only {len(candidates)} live matchmakers; epoch change "
                f"needs {needed} -- skipped")
            return
        subset = sorted(self.rng.sample(candidates, needed))
        self.send(self.config.reconfigurer_addresses[0],
                  ReconfigureMatchmakers(
                      matchmaker_configuration=
                      self.matchmaker_configuration,
                      new_matchmaker_indices=tuple(subset)))

    def kill_matchmaker(self, index: int) -> None:
        self._killed.add(index)
        self.send(self.config.matchmaker_addresses[index], Die())

    # --- schedule wiring ---------------------------------------------------
    def _delayed_repeating(self, name: str, delay_s: float,
                           period_s: float, n: int, fire) -> None:
        from frankenpaxos_tpu.protocols.driver_util import delayed_repeating

        self.timers += delayed_repeating(self, name, delay_s, period_s, n,
                                         fire)

    def _once(self, name: str, delay_s: float, fire) -> None:
        t = self.timer(name, delay_s, fire)
        t.start()
        self.timers.append(t)

    def _start(self) -> None:
        w = self.workload
        if isinstance(w, DriverDoNothing):
            return
        if isinstance(w, DriverRepeatedReconfiguration):
            from frankenpaxos_tpu.protocols.driver_util import repeating

            self.timers += repeating(self, "reconfigure", w.delay_s,
                                     w.period_s,
                                     self.reconfigure_acceptors)
            return
        if isinstance(w, DriverMatchmakerReconfiguration):
            self._delayed_repeating("warmup", w.warmup_delay_s,
                                    w.warmup_period_s, w.warmup_num,
                                    self.reconfigure_acceptors)
            self._delayed_repeating("mmReconfigure", w.matchmaker_delay_s,
                                    w.matchmaker_period_s,
                                    w.matchmaker_num,
                                    self.reconfigure_matchmakers)
            return
        if isinstance(w, DriverChaos):
            self._delayed_repeating("warmup", w.warmup_delay_s,
                                    w.warmup_period_s, w.warmup_num,
                                    self.reconfigure_acceptors)
            self._once("matchmakerFailure", w.matchmaker_failure_delay_s,
                       lambda: self.kill_matchmaker(self.rng.choice(
                           self.matchmaker_configuration
                           .matchmaker_indices)))
            self._once("matchmakerRecover", w.matchmaker_recover_delay_s,
                       self.reconfigure_matchmakers)
            self._once("acceptorFailure", w.acceptor_failure_delay_s,
                       self.reconfigure_acceptors)
            self._once("acceptorRecover", w.acceptor_recover_delay_s,
                       self.reconfigure_acceptors)
            return
        self.logger.fatal(f"unknown driver workload {w!r}")

    def receive(self, src: Address, message) -> None:
        if isinstance(message, MatchChosen):
            # The reconfigurer bounced a stale-epoch request; retry with
            # the fresh epoch so scheduled churn isn't silently halved.
            self.matchmaker_configuration = message.value
            self.reconfigure_matchmakers()
            return
        self.logger.fatal(f"driver got unexpected message {message!r}")

# Importing registers the steady-state binary codecs with the hybrid
# serializer (see matchmakermultipaxos_wire.py).
from frankenpaxos_tpu.protocols import matchmakermultipaxos_wire  # noqa: E402,F401
