"""Fast MultiPaxos: a log of fast and classic rounds.

Reference behavior: fastmultipaxos/ (Leader.scala:35-1350,
Acceptor.scala:60-520, Config.scala). In a fast round, the leader sends
acceptors a distinguished "anySuffix" after phase 1; acceptors then vote
directly for client ProposeRequests in their next open slot, and the
leader collects Phase2bs:

  * fast ready: some value has fastQuorumSize (= f + majority-of-f+1)
    votes -> chosen;
  * fast stuck: no value can still reach a fast quorum -> coordinated
    recovery via the next (classic) round;
  * classic rounds work like MultiPaxos with explicit Phase2as.

Phase-1 recovery uses Fast Paxos's rule: at the max vote round k, a
unique value wins; else a value with >= majority-of-quorum votes wins;
else any (noop). Chosen values are gossiped to other leaders
(ValueChosen) so standbys maintain the log. Election is raft-style
(election/raft).

Liveness/performance knobs:
  * thrifty quorums (Leader.scala:464-500): the leader sends Phase1as
    and classic Phase2as to only quorum-size acceptors chosen by a
    ThriftySystem (with the reference's placeholder uniform delays);
  * wait/stagger buffering (Acceptor.scala:60-90, 200-230): acceptors
    optionally buffer direct client proposals and process them in
    deterministically-sorted batches every wait_period, a heuristic
    that cuts fast-path conflicts; resulting Phase2bs travel in one
    Phase2bBuffer.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Union

from frankenpaxos_tpu.election.raft import (
    RaftElectionOptions,
    RaftElectionParticipant,
)
from frankenpaxos_tpu.heartbeat import HeartbeatOptions, HeartbeatParticipant
from frankenpaxos_tpu.roundsystem import RoundSystem, RoundType
from frankenpaxos_tpu.runs.quorums import fast_flexible_specs, SpecChecker
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.thrifty import ThriftySystem


@dataclasses.dataclass(frozen=True)
class FastMultiPaxosConfig:
    f: int
    leader_addresses: tuple
    leader_election_addresses: tuple
    leader_heartbeat_addresses: tuple
    acceptor_addresses: tuple
    acceptor_heartbeat_addresses: tuple
    round_system: RoundSystem

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum_size(self) -> int:
        return self.f + 1

    @property
    def quorum_majority_size(self) -> int:
        return (self.f + 1) // 2 + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.f + self.quorum_majority_size

    def check_valid(self) -> None:
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError("need exactly 2f+1 acceptors")

    def quorum_size(self, round: int) -> int:
        if self.round_system.round_type(round) == RoundType.FAST:
            return self.fast_quorum_size
        return self.classic_quorum_size


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
Value = Union[Command, Noop]


@dataclasses.dataclass(frozen=True)
class ProposeRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ProposeReply:
    command_id: CommandId
    result: bytes
    # The replying leader's round: clients track it to route classic-
    # round proposals to the right leader (Client.scala:92-103, :182).
    round: int = 0


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    chosen_watermark: int
    chosen_slots: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Phase1bVote:
    slot: int
    vote_round: int
    value: Value


@dataclasses.dataclass(frozen=True)
class Phase1b:
    acceptor_id: int
    round: int
    votes: tuple[Phase1bVote, ...]


@dataclasses.dataclass(frozen=True)
class Phase1bNack:
    acceptor_id: int
    round: int


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    # A concrete value, or "any" markers (fast rounds only).
    value: Optional[Value] = None
    any: bool = False
    any_suffix: bool = False


@dataclasses.dataclass(frozen=True)
class Phase2b:
    acceptor_id: int
    slot: int
    round: int
    vote: Value


@dataclasses.dataclass(frozen=True)
class Phase2bBuffer:
    """A batch of Phase2bs from one acceptor drain
    (Acceptor.scala:215-229)."""

    phase2bs: tuple[Phase2b, ...]


@dataclasses.dataclass(frozen=True)
class ValueChosen:
    slot: int
    value: Value


@dataclasses.dataclass
class _AcceptorEntry:
    vote_round: int = -1
    vote_value: Optional[Value] = None
    any_round: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class FastMultiPaxosAcceptorOptions:
    """Conflict-avoidance buffering of direct client proposals
    (AcceptorOptions, Acceptor.scala:60-90). With both zero, proposals
    are processed immediately."""

    wait_period_s: float = 0.0
    wait_stagger_s: float = 0.0


class FastMultiPaxosAcceptor(Actor):
    """(fastmultipaxos/Acceptor.scala:60-520)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FastMultiPaxosConfig,
                 options: FastMultiPaxosAcceptorOptions =
                 FastMultiPaxosAcceptorOptions(),
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.clock = clock
        self.acceptor_id = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.log: dict[int, _AcceptorEntry] = {}
        self.next_slot = 0
        # An "anySuffix" round covers every slot >= its start.
        self.any_suffix: Optional[tuple[int, int]] = None  # (slot, round)
        self.heartbeat = HeartbeatParticipant(
            config.acceptor_heartbeat_addresses[self.acceptor_id], transport,
            logger, list(config.acceptor_heartbeat_addresses),
            HeartbeatOptions())
        # Wait/stagger buffering (Acceptor.scala:140-160).
        self.buffered_proposals: list[
            tuple[float, Address, ProposeRequest]] = []
        self._wait_timer = None
        if options.wait_period_s > 0 or options.wait_stagger_s > 0:
            def process():
                self._process_buffered_proposals()
                self._wait_timer.start()

            self._wait_timer = self.timer(
                "processBufferedProposeRequests", options.wait_period_s,
                process)
            self._wait_timer.start()

    def _entry(self, slot: int) -> _AcceptorEntry:
        entry = self.log.get(slot)
        if entry is None:
            entry = _AcceptorEntry()
            if self.any_suffix is not None \
                    and slot >= self.any_suffix[0]:
                entry.any_round = self.any_suffix[1]
            self.log[slot] = entry
        return entry

    def _leader_of(self, round: int) -> Address:
        return self.config.leader_addresses[
            self.config.round_system.leader(round)]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeRequest):
            self._handle_propose_request(src, message)
        elif isinstance(message, Phase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_propose_request(self, src: Address,
                                request: ProposeRequest) -> None:
        if self._wait_timer is not None:
            self.buffered_proposals.append((self.clock(), src, request))
            return
        phase2b = self._process_propose_request(src, request)
        if phase2b is not None:
            self.send(self._leader_of(self.round), phase2b)

    def _process_propose_request(self, src: Address,
                                 request: ProposeRequest
                                 ) -> Optional[Phase2b]:
        """Vote directly in our next open slot iff it carries the current
        round's any marker (Acceptor.scala:220-236)."""
        entry = self._entry(self.next_slot)
        if entry.any_round == self.round and entry.vote_round < self.round:
            entry.vote_round = self.round
            entry.vote_value = request.command
            entry.any_round = None
            phase2b = Phase2b(acceptor_id=self.acceptor_id,
                              slot=self.next_slot, round=self.round,
                              vote=request.command)
            self.next_slot += 1
            return phase2b
        return None

    def _process_buffered_proposals(self) -> None:
        """Drain proposals older than the stagger cutoff in a
        deterministic order (processBufferedProposeRequests,
        Acceptor.scala:200-230): identically-configured acceptors that
        buffered the same conflicting proposals vote on them in the
        same order, avoiding fast-path conflicts."""
        cutoff = self.clock() - self.options.wait_stagger_s
        take = 0
        while take < len(self.buffered_proposals) \
                and self.buffered_proposals[take][0] <= cutoff:
            take += 1
        batch = self.buffered_proposals[:take]
        del self.buffered_proposals[:take]
        phase2bs = []
        # Deterministic (hash-seed independent) sort key.
        for _, src, request in sorted(
                batch,
                key=lambda b: (repr(b[1]),
                               repr(b[2].command.command_id),
                               b[2].command.command)):
            phase2b = self._process_propose_request(src, request)
            if phase2b is not None:
                phase2bs.append(phase2b)
        if phase2bs:
            self.send(self._leader_of(self.round),
                      Phase2bBuffer(tuple(phase2bs)))

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round <= self.round:
            self.send(src, Phase1bNack(acceptor_id=self.acceptor_id,
                                       round=self.round))
            return
        self.round = phase1a.round
        votes = tuple(
            Phase1bVote(slot=slot, vote_round=entry.vote_round,
                        value=entry.vote_value)
            for slot, entry in sorted(self.log.items())
            if slot >= phase1a.chosen_watermark
            and slot not in phase1a.chosen_slots
            and entry.vote_value is not None)
        self.send(self._leader_of(self.round),
                  Phase1b(acceptor_id=self.acceptor_id, round=self.round,
                          votes=votes))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        """(Acceptor.scala processPhase2a)."""
        if phase2a.round < self.round:
            return
        if phase2a.any_suffix:
            self.round = phase2a.round
            self.any_suffix = (phase2a.slot, phase2a.round)
            for slot, entry in self.log.items():
                if slot >= phase2a.slot:
                    entry.any_round = phase2a.round
            if self.next_slot < phase2a.slot:
                self.next_slot = phase2a.slot
            return
        if phase2a.any:
            self.round = phase2a.round
            self._entry(phase2a.slot).any_round = phase2a.round
            return
        entry = self._entry(phase2a.slot)
        if phase2a.round == entry.vote_round:
            # Already voted this round; re-relay for liveness.
            self.send(self._leader_of(self.round),
                      Phase2b(acceptor_id=self.acceptor_id,
                              slot=phase2a.slot, round=entry.vote_round,
                              vote=entry.vote_value))
            return
        self.round = phase2a.round
        entry.vote_round = phase2a.round
        entry.vote_value = phase2a.value
        entry.any_round = None
        if phase2a.slot >= self.next_slot:
            self.next_slot = phase2a.slot + 1
        self.send(self._leader_of(self.round),
                  Phase2b(acceptor_id=self.acceptor_id, slot=phase2a.slot,
                          round=phase2a.round, vote=phase2a.value))


@dataclasses.dataclass
class _Phase1State:
    phase1bs: dict[int, Phase1b]
    pending_proposals: list[tuple[Address, Command]]


@dataclasses.dataclass
class _Phase2State:
    pending_entries: dict[int, Value]
    phase2bs: dict[int, dict[int, Phase2b]]


@dataclasses.dataclass(frozen=True)
class FastMultiPaxosLeaderOptions:
    """LeaderOptions (Leader.scala:30-60). ``thrifty_system`` None
    means send to every acceptor."""

    thrifty_system: Optional[ThriftySystem] = None
    resend_phase1as_period_s: float = 5.0
    # Also the fast-stuck detection period: a fast round that makes no
    # progress for a full period falls back to a classic round.
    resend_phase2as_period_s: float = 5.0
    # "host": NumPy quorum-spec evaluation; "tpu": the fused ops/quorum
    # checker (runs/quorums.SpecChecker) -- bit-identical predicates.
    quorum_backend: str = "host"


class FastMultiPaxosLeader(Actor):
    """(fastmultipaxos/Leader.scala:35-1350)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FastMultiPaxosConfig,
                 state_machine: StateMachine,
                 options: FastMultiPaxosLeaderOptions =
                 FastMultiPaxosLeaderOptions(),
                 election_options: RaftElectionOptions =
                 RaftElectionOptions(), seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        # Classic/fast/recovery predicates in matrix form, sized from
        # the LIVE config (runs/quorums.py).
        specs = fast_flexible_specs(config.n, config.classic_quorum_size,
                                    config.fast_quorum_size)
        self.classic_quorum = SpecChecker(
            specs.classic, options.quorum_backend,
            metrics=lambda: transport.runtime_metrics)
        self.fast_quorum = SpecChecker(
            specs.fast, options.quorum_backend,
            metrics=lambda: transport.runtime_metrics)
        self.recovery_quorum = SpecChecker(
            specs.recovery, options.quorum_backend,
            metrics=lambda: transport.runtime_metrics)
        self.leader_id = list(config.leader_addresses).index(address)
        self.round = 0 if config.round_system.leader(0) == self.leader_id \
            else -1
        self.log: dict[int, Value] = {}
        self.chosen_watermark = 0
        self.next_slot = 0
        self.client_table: dict[Address, tuple[int, bytes]] = {}
        # Leaders monitor the ACCEPTORS (Leader.scala:341-353): the
        # alive count gates fast rounds and the delay estimates feed
        # thrifty Closest selection.
        self.heartbeat = HeartbeatParticipant(
            config.leader_heartbeat_addresses[self.leader_id], transport,
            logger, list(config.acceptor_heartbeat_addresses),
            HeartbeatOptions())
        # Liveness: thrifty sends target a bare quorum, so resends go to
        # every acceptor (resendPhase1as/resendPhase2as timers,
        # Leader.scala:355-376).

        def resend_phase1as():
            if isinstance(self.state, _Phase1State):
                self._send_phase1as(thrifty=False)
            self.resend_phase1as_timer.start()

        def resend_phase2as():
            # Fast rounds can wedge without ever looking "stuck" to the
            # per-slot conflict test: acceptors vote a command at their
            # own next_slot, so offset acceptors spread one command over
            # adjacent slots, each collecting an unchoosable-but-
            # "possible" partial quorum forever. If a full resend period
            # passes with votes outstanding and nothing chosen, fall
            # back to coordinated recovery in the next (classic) round
            # (Leader.scala:365-376 + the fast-stuck path of
            # processPhase2b, Leader.scala:690-724).
            progress = (self.chosen_watermark, len(self.log))
            if (isinstance(self.state, _Phase2State)
                    and self.state.phase2bs
                    and progress == self._last_progress
                    and self.config.round_system.round_type(self.round)
                    == RoundType.FAST):
                # Force a CLASSIC round: jumping to another fast round
                # recreates the same offset-votes wedge.
                self._bump_round_and_restart(self.round,
                                             force_classic=True)
                return
            self._last_progress = progress
            self._resend_phase2as()
            self.resend_phase2as_timer.start()

        self._last_progress = (-1, -1)
        self.resend_phase1as_timer = self.timer(
            "resendPhase1as", options.resend_phase1as_period_s,
            resend_phase1as)
        self.resend_phase2as_timer = self.timer(
            "resendPhase2as", options.resend_phase2as_period_s,
            resend_phase2as)
        self.election = RaftElectionParticipant(
            config.leader_election_addresses[self.leader_id], transport,
            logger, list(config.leader_election_addresses),
            leader=config.leader_election_addresses[0],
            options=election_options, seed=seed)
        self.election.register(self._on_leader_change)

        if self.round == 0:
            self._send_phase1as()
            self.state: object = _Phase1State({}, [])
            self.resend_phase1as_timer.start()
        else:
            self.state = None  # Inactive

    # --- helpers ----------------------------------------------------------
    def _other_leaders(self):
        return [a for a in self.config.leader_addresses if a != self.address]

    def _thrifty_acceptors(self, min_size: int) -> list[Address]:
        """thriftyAcceptors (Leader.scala:464-483): pick at least
        ``min_size`` acceptors via the thrifty system, fed by the
        heartbeat's delay estimates (dead acceptors report infinite
        delay, so Closest avoids them)."""
        if self.options.thrifty_system is None:
            return list(self.config.acceptor_addresses)
        delays_by_hb = self.heartbeat.unsafe_network_delay()
        delays = {
            self.config.acceptor_addresses[i]: delays_by_hb.get(hb, 0.0)
            for i, hb in enumerate(
                self.config.acceptor_heartbeat_addresses)}
        return sorted(self.options.thrifty_system.choose(
            delays, min_size, self.rng))

    def _resend_phase2as(self) -> None:
        """Re-send every pending Phase2a to every acceptor
        (Leader.scala:365-376)."""
        if not isinstance(self.state, _Phase2State):
            return
        for slot, value in self.state.pending_entries.items():
            phase2a = Phase2a(slot=slot, round=self.round, value=value)
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, phase2a)

    def _send_phase1as(self, thrifty: bool = False) -> None:
        phase1a = Phase1a(round=self.round,
                          chosen_watermark=self.chosen_watermark,
                          chosen_slots=tuple(
                              s for s in sorted(self.log)
                              if s >= self.chosen_watermark))
        targets = (self._thrifty_acceptors(self.config.classic_quorum_size)
                   if thrifty else self.config.acceptor_addresses)
        for acceptor in targets:
            self.send(acceptor, phase1a)

    def _on_leader_change(self, leader_address: Address) -> None:
        is_me = (leader_address
                 == self.config.leader_election_addresses[self.leader_id])
        if not is_me:
            self.state = None
            self.resend_phase1as_timer.stop()
            self.resend_phase2as_timer.stop()
            return
        self._bump_round_and_restart(self.round, thrifty=False)

    def _bump_round_and_restart(self, higher_than: int,
                                thrifty: bool = True,
                                force_classic: bool = False) -> None:
        rs = self.config.round_system
        if not force_classic and len(
                self.heartbeat.unsafe_alive()) >= self.config.fast_quorum_size:
            next_fast = rs.next_fast_round(self.leader_id, higher_than)
            self.round = (next_fast if next_fast is not None
                          else rs.next_classic_round(self.leader_id,
                                                     higher_than))
        else:
            self.round = rs.next_classic_round(self.leader_id, higher_than)
        # Nack/stuck-driven restarts are thrifty (Leader.scala:433); the
        # initial round and election-driven takeovers are not (:359).
        self._send_phase1as(thrifty=thrifty)
        self.state = _Phase1State({}, [])
        self.resend_phase2as_timer.stop()
        self.resend_phase1as_timer.start()

    def _choose_proposal(self, phase1bs: dict[int, Phase1b],
                         slot: int) -> Value:
        """Fast Paxos phase-1 value selection (Leader.scala:482-530).

        At max vote round k, a unique value wins; else a value whose
        round-k voters satisfy the recovery spec (>= q1 + qf - n of
        them, i.e. fast-quorum intersection demands adoption) wins;
        else any round-k vote. An ambiguity between popular values is
        only possible when the configuration violates the fast
        intersection condition; adoption is then not forced."""
        votes = []
        for acceptor_id, phase1b in phase1bs.items():
            vote = next((v for v in phase1b.votes if v.slot == slot), None)
            votes.append((acceptor_id, -1, None) if vote is None
                         else (acceptor_id, vote.vote_round, vote.value))
        k = max(vote_round for _, vote_round, _ in votes)
        if k == -1:
            return NOOP
        at_k = [(acceptor_id, value)
                for acceptor_id, vote_round, value in votes
                if vote_round == k]
        if len({value for _, value in at_k}) == 1:
            return at_k[0][1]
        voters: dict[Value, list[int]] = {}
        for acceptor_id, value in at_k:
            voters.setdefault(value, []).append(acceptor_id)
        popular = [value for value, ids in voters.items()
                   if self.recovery_quorum.check(ids)]
        if len(popular) == 1:
            return popular[0]
        return at_k[0][1]

    def _choose(self, slot: int, value: Value) -> None:
        if slot in self.log:
            return
        self.log[slot] = value
        if isinstance(self.state, _Phase2State):
            self.state.pending_entries.pop(slot, None)
            self.state.phase2bs.pop(slot, None)
        for leader in self._other_leaders():
            self.send(leader, ValueChosen(slot=slot, value=value))
        self._execute_log()

    def _execute_log(self) -> None:
        while self.chosen_watermark in self.log:
            value = self.log[self.chosen_watermark]
            slot = self.chosen_watermark
            self.chosen_watermark += 1
            if slot + 1 > self.next_slot:
                self.next_slot = slot + 1
            if isinstance(value, Noop):
                continue
            cid = value.command_id
            cached = self.client_table.get(cid.client_address)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(value.command)
                self.client_table[cid.client_address] = (cid.client_id,
                                                         result)
            if self.state is not None:  # only the active leader replies
                self.send(cid.client_address,
                          ProposeReply(command_id=cid, result=result,
                                       round=self.round))

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ProposeRequest):
            self._handle_propose_request(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase1bNack):
            self._handle_phase1b_nack(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Phase2bBuffer):
            for phase2b in message.phase2bs:
                self._handle_phase2b(src, phase2b)
        elif isinstance(message, ValueChosen):
            self._handle_value_chosen(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_propose_request(self, src: Address,
                                request: ProposeRequest) -> None:
        cid = request.command.command_id
        cached = self.client_table.get(cid.client_address)
        if cached is not None and cid.client_id == cached[0]:
            # Only the ACTIVE leader replies (matching _execute_log): a
            # deposed leader's self.round may never have been
            # established at any acceptor, and the client adopts reply
            # rounds monotonically -- a stale reply would permanently
            # misroute its classic-round proposals to this dead leader.
            if self.state is not None:
                self.send(cid.client_address,
                          ProposeReply(command_id=cid, result=cached[1],
                                       round=self.round))
            return
        if isinstance(self.state, _Phase1State):
            self.state.pending_proposals.append((src, request.command))
            return
        if not isinstance(self.state, _Phase2State):
            return  # inactive; the active leader will handle it
        if self.config.round_system.round_type(self.round) \
                == RoundType.FAST:
            return  # clients propose straight to acceptors in fast rounds
        slot = self.next_slot
        self.next_slot += 1
        self.state.pending_entries[slot] = request.command
        phase2a = Phase2a(slot=slot, round=self.round,
                          value=request.command)
        for acceptor in self._thrifty_acceptors(
                self.config.quorum_size(self.round)):
            self.send(acceptor, phase2a)

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1State) \
                or phase1b.round != self.round:
            return
        state = self.state
        state.phase1bs[phase1b.acceptor_id] = phase1b
        if not self.classic_quorum.check(state.phase1bs):
            return
        # Fill every unchosen slot up to the max voted slot.
        max_slot = max(
            (vote.slot for p in state.phase1bs.values()
             for vote in p.votes), default=-1)
        phase2 = _Phase2State({}, {})
        for slot in range(self.chosen_watermark, max_slot + 1):
            if slot in self.log:
                continue
            value = self._choose_proposal(state.phase1bs, slot)
            phase2.pending_entries[slot] = value
            for acceptor in self._thrifty_acceptors(
                    self.config.quorum_size(self.round)):
                self.send(acceptor, Phase2a(slot=slot, round=self.round,
                                            value=value))
        # next_slot >= chosen_watermark is an invariant here: the ONLY
        # place chosen_watermark advances (the execute loop in
        # _choose) lifts next_slot alongside it, and every chosen slot
        # >= the watermark carries f+1 votes so the Phase1 read quorum
        # reports it (max_slot covers it). This max() therefore cannot
        # land the proposal cursor inside chosen state.
        # paxlint: disable=SAFE903
        self.next_slot = max(self.next_slot, max_slot + 1)
        pending = state.pending_proposals
        self.state = phase2
        self.resend_phase1as_timer.stop()
        self.resend_phase2as_timer.start()
        if self.config.round_system.round_type(self.round) \
                == RoundType.FAST:
            # Open the suffix for direct client proposals.
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, Phase2a(slot=self.next_slot,
                                            round=self.round,
                                            any_suffix=True))
        else:
            for src_addr, command in pending:
                self._handle_propose_request(src_addr,
                                             ProposeRequest(command))

    def _handle_phase1b_nack(self, src: Address,
                             nack: Phase1bNack) -> None:
        if nack.round <= self.round or self.state is None:
            return
        self._bump_round_and_restart(nack.round)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        """(Leader.scala:690-724 phase2bChosenInSlot + processPhase2b)."""
        if not isinstance(self.state, _Phase2State) \
                or phase2b.round != self.round:
            return
        if phase2b.slot in self.log:
            return
        state = self.state
        in_slot = state.phase2bs.setdefault(phase2b.slot, {})
        in_slot[phase2b.acceptor_id] = phase2b
        round_type = self.config.round_system.round_type(self.round)
        if round_type == RoundType.CLASSIC:
            if self.classic_quorum.check(in_slot):
                self._choose(phase2b.slot,
                             state.pending_entries[phase2b.slot])
            return
        # Fast round.
        if not self.classic_quorum.check(in_slot):
            return
        voters: dict[Value, list[int]] = {}
        for acceptor_id, p in in_slot.items():
            voters.setdefault(p.vote, []).append(acceptor_id)
        votes_left = self.config.n - len(in_slot)
        if not any(len(ids) + votes_left >= self.config.fast_quorum_size
                   for ids in voters.values()):
            # Fast stuck: coordinated recovery in the next round.
            self._bump_round_and_restart(self.round)
            return
        for value, ids in voters.items():
            if self.fast_quorum.check(ids):
                self._choose(phase2b.slot, value)
                return

    def _handle_value_chosen(self, src: Address,
                             message: ValueChosen) -> None:
        if message.slot not in self.log:
            self.log[message.slot] = message.value
            self._execute_log()


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class FastMultiPaxosClient(Actor):
    """Routes by its guess of the current round (Client.scala:92-103,
    :216-223): FAST rounds propose straight to every acceptor; CLASSIC
    rounds propose to the round's leader (acceptors ignore direct
    proposals outside fast rounds, so sending them there would strand
    the command until the resend timer). The guess updates from each
    ProposeReply; resends cover a stale guess."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FastMultiPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.round = 0
        self.next_id = 0
        self.pending: Optional[_Pending] = None

    def _send_proposal(self, request: ProposeRequest) -> None:
        rs = self.config.round_system
        if rs.round_type(self.round) == RoundType.FAST:
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, request)
        else:
            self.send(self.config.leader_addresses[rs.leader(self.round)],
                      request)

    def propose(self, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        if self.pending is not None:
            raise RuntimeError("a proposal is already pending")
        id = self.next_id
        self.next_id += 1
        request = ProposeRequest(Command(CommandId(self.address, id),
                                         command))
        self._send_proposal(request)

        def resend():
            for leader in self.config.leader_addresses:
                self.send(leader, request)
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, request)
            timer.start()

        timer = self.timer(f"resend-{id}", self.resend_period_s, resend)
        timer.start()
        self.pending = _Pending(id, command, callback or (lambda _: None),
                                timer)

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ProposeReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        self.round = max(self.round, message.round)
        if self.pending is None \
                or message.command_id.client_id != self.pending.id:
            return
        pending = self.pending
        pending.resend.stop()
        self.pending = None
        pending.callback(message.result)

# Importing registers this protocol's binary codecs with the hybrid
# serializer (see fastmultipaxos_wire.py).
from frankenpaxos_tpu.protocols import fastmultipaxos_wire  # noqa: E402,F401
