"""BatchedUnreplicated: Batcher -> Server -> ProxyServer pipeline.

Reference behavior: batchedunreplicated/ (Batcher.scala:29-160,
Server.scala:30-170, ProxyServer.scala:30-150, Client.scala:33-170).
The batching throughput baseline: batchers accumulate client commands
into batches, one server executes them, proxy servers fan the replies
back out -- decoupling the three stages so each scales independently.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine


@dataclasses.dataclass(frozen=True)
class BatchedUnreplicatedConfig:
    batcher_addresses: tuple
    server_address: Address
    proxy_server_addresses: tuple

    def check_valid(self) -> None:
        if not self.batcher_addresses:
            raise ValueError("need at least one batcher")
        if not self.proxy_server_addresses:
            raise ValueError("need at least one proxy server")


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientRequestBatch:
    batch: tuple[Command, ...]


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    result: bytes


@dataclasses.dataclass(frozen=True)
class ClientReplyBatch:
    batch: tuple[ClientReply, ...]


class BatchedUnreplicatedBatcher(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: BatchedUnreplicatedConfig,
                 batch_size: int = 10):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check_ge(batch_size, 1)
        self.config = config
        self.batch_size = batch_size
        self.growing_batch: list[Command] = []

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientRequest):
            self.logger.fatal(f"unexpected batcher message {message!r}")
        self.growing_batch.append(message.command)
        if len(self.growing_batch) >= self.batch_size:
            self.send(self.config.server_address,
                      ClientRequestBatch(tuple(self.growing_batch)))
            self.growing_batch.clear()


class BatchedUnreplicatedServer(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: BatchedUnreplicatedConfig,
                 state_machine: StateMachine, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientRequestBatch):
            self.logger.fatal(f"unexpected server message {message!r}")
        replies = tuple(
            ClientReply(command.command_id,
                        self.state_machine.run(command.command))
            for command in message.batch)
        proxy = self.config.proxy_server_addresses[
            self.rng.randrange(len(self.config.proxy_server_addresses))]
        self.send(proxy, ClientReplyBatch(replies))


class BatchedUnreplicatedProxyServer(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: BatchedUnreplicatedConfig,
                 flush_every_n: int = 1):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.flush_every_n = flush_every_n
        self._unflushed = 0
        self._unflushed_clients: set[Address] = set()

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReplyBatch):
            self.logger.fatal(f"unexpected proxy server message {message!r}")
        for reply in message.batch:
            dst = reply.command_id.client_address
            if self.flush_every_n <= 1:
                self.send(dst, reply)
            else:
                self.send_no_flush(dst, reply)
                self._unflushed_clients.add(dst)
                self._unflushed += 1
                if self._unflushed >= self.flush_every_n:
                    for client in self._unflushed_clients:
                        self.flush(client)
                    self._unflushed_clients.clear()
                    self._unflushed = 0


@dataclasses.dataclass
class _Pending:
    command: bytes
    callback: Callable[[bytes], None]
    resend_timer: object


class BatchedUnreplicatedClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: BatchedUnreplicatedConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.next_id = 0
        self.pending: dict[int, _Pending] = {}

    def propose(self, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        id = self.next_id
        self.next_id += 1
        request = ClientRequest(Command(CommandId(self.address, id), command))

        def send_it():
            batcher = self.config.batcher_addresses[
                self.rng.randrange(len(self.config.batcher_addresses))]
            self.send(batcher, request)

        def resend():
            send_it()
            timer.start()

        send_it()
        timer = self.timer(f"resend-{id}", self.resend_period_s, resend)
        timer.start()
        self.pending[id] = _Pending(command, callback or (lambda _: None),
                                    timer)

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.pop(message.command_id.client_id, None)
        if pending is None:
            self.logger.debug(f"stale reply {message}")
            return
        pending.resend_timer.stop()
        pending.callback(message.result)


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
