"""Binary codecs for the FastMultiPaxos steady-state path.

Covers the whole per-command loop: direct client proposals
(ProposeRequest), the leader/acceptor Phase2a (including the fast-round
any/anySuffix markers), per-vote Phase2b and the acceptor-drain
Phase2bBuffer, the ValueChosen gossip, and ProposeReply. Phase 1 /
election traffic is per-failover and stays pickled."""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import fastmultipaxos as fmp
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")

# --- FastMultiPaxos ---------------------------------------------------------


def _fmp_put_command(out: bytearray, command: fmp.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64.pack(cid.client_id)
    _put_bytes(out, command.command)


def _fmp_take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    (client_id,) = _I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 8)
    return fmp.Command(fmp.CommandId(address, client_id), payload), at


class FMPProposeRequestCodec(MessageCodec):
    message_type = fmp.ProposeRequest
    tag = 70

    def encode(self, out, message):
        _fmp_put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _fmp_take_command(buf, at)
        return fmp.ProposeRequest(command), at


class FMPProposeReplyCodec(MessageCodec):
    message_type = fmp.ProposeReply
    tag = 71

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_id, message.round)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        client_id, round = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return fmp.ProposeReply(fmp.CommandId(address, client_id),
                                result, round=round), at



def _fmp_put_value(out: bytearray, value) -> None:
    if isinstance(value, fmp.Noop):
        out.append(0)
    else:
        out.append(1)
        _fmp_put_command(out, value)


def _fmp_take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return fmp.NOOP, at
    return _fmp_take_command(buf, at)


class FMPPhase2aCodec(MessageCodec):
    """value None / any / anySuffix pack into one kind byte."""

    message_type = fmp.Phase2a
    tag = 72

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        if message.any_suffix:
            out.append(3)
        elif message.any:
            out.append(2)
        elif message.value is None:
            out.append(4)
        else:
            _fmp_put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        at += 16
        kind = buf[at]
        if kind in (2, 3, 4):
            at += 1
            return fmp.Phase2a(
                slot=slot, round=round, value=None,
                any=(kind == 2), any_suffix=(kind == 3)), at
        value, at = _fmp_take_value(buf, at)
        return fmp.Phase2a(slot=slot, round=round, value=value), at


class FMPPhase2bCodec(MessageCodec):
    message_type = fmp.Phase2b
    tag = 73

    def encode(self, out, message):
        out += _QQQ.pack(message.acceptor_id, message.slot, message.round)
        _fmp_put_value(out, message.vote)

    def decode(self, buf, at):
        acceptor, slot, round = _QQQ.unpack_from(buf, at)
        vote, at = _fmp_take_value(buf, at + _QQQ.size)
        return fmp.Phase2b(acceptor_id=acceptor, slot=slot, round=round,
                           vote=vote), at


class FMPPhase2bBufferCodec(MessageCodec):
    message_type = fmp.Phase2bBuffer
    tag = 74

    def encode(self, out, message):
        out += _I32.pack(len(message.phase2bs))
        inner = FMPPhase2bCodec()
        for phase2b in message.phase2bs:
            inner.encode(out, phase2b)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        inner = FMPPhase2bCodec()
        phase2bs = []
        for _ in range(n):
            phase2b, at = inner.decode(buf, at)
            phase2bs.append(phase2b)
        return fmp.Phase2bBuffer(tuple(phase2bs)), at


class FMPValueChosenCodec(MessageCodec):
    message_type = fmp.ValueChosen
    tag = 75

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _fmp_put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _fmp_take_value(buf, at + 8)
        return fmp.ValueChosen(slot=slot, value=value), at


class FMPPhase1bNackCodec(MessageCodec):
    """Round-race feedback on the fast path (COD301 burn-down, paxwire
    extended tag page): per-failover, but a failover storm is when the
    wire is busiest."""

    message_type = fmp.Phase1bNack
    tag = 157

    def encode(self, out, message):
        out += _I64I64.pack(message.acceptor_id, message.round)

    def decode(self, buf, at):
        acceptor_id, round = _I64I64.unpack_from(buf, at)
        return fmp.Phase1bNack(acceptor_id=acceptor_id,
                               round=round), at + 16


for _codec in (FMPProposeRequestCodec(), FMPProposeReplyCodec(),
               FMPPhase2aCodec(), FMPPhase2bCodec(),
               FMPPhase2bBufferCodec(), FMPValueChosenCodec(),
               FMPPhase1bNackCodec()):
    register_codec(_codec)
