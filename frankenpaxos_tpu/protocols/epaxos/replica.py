"""EPaxos Replica: leaderless generalized consensus, all roles in one.

Reference behavior: epaxos/Replica.scala:390-1940. Every replica owns a
column of instances (replica_index, 0..); commands are PreAccepted with
conflict-derived dependency sets, committed on the fast path when
``fast_quorum_size`` (= n-1) replies carry identical (seq, deps), else
Accepted through a classic f+1 round; committed commands execute in
dependency-graph SCC order with exactly-once client-table semantics.
Failure recovery runs explicit-prepare ballots (Prepare/PrepareOk,
Replica.scala:1632-1940) driven by randomized recover-instance timers on
blocking dependencies.
"""

from __future__ import annotations

from collections import Counter as _Counter
import dataclasses
import random
from typing import Optional, Union

from frankenpaxos_tpu.clienttable import ClientTable, Executed, NOT_EXECUTED
from frankenpaxos_tpu.depgraph import make_dependency_graph
from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    Instance,
    InstancePrefixSet,
)
from frankenpaxos_tpu.protocols.epaxos.messages import (
    Accept,
    AcceptOk,
    Ballot,
    ClientReply,
    ClientRequest,
    Command,
    CommandStatus,
    Commit,
    Nack,
    NOOP,
    Noop,
    NULL_BALLOT,
    PreAccept,
    PreAcceptOk,
    Prepare,
    PrepareOk,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils.topk import TUPLE_VERTEX_LIKE

@dataclasses.dataclass(frozen=True)
class EPaxosConfig:
    f: int
    replica_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.n - 1

    @property
    def slow_quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if len(self.replica_addresses) != self.n:
            raise ValueError(
                f"need 2f+1 = {self.n} replicas, got "
                f"{len(self.replica_addresses)}")


@dataclasses.dataclass(frozen=True)
class EPaxosReplicaOptions:
    top_k_dependencies: int = 1
    execute_graph_batch_size: int = 1
    execute_graph_timer_period_s: float = 1.0
    resend_pre_accepts_period_s: float = 10.0
    default_to_slow_path_period_s: float = 10.0
    resend_accepts_period_s: float = 10.0
    resend_prepares_period_s: float = 10.0
    recover_instance_min_period_s: float = 20.0
    recover_instance_max_period_s: float = 40.0
    unsafe_skip_graph_execution: bool = False
    num_blockers: Optional[int] = 1
    # "tarjan", "incremental", or "zigzag" (the reference's ReplicaMain
    # hardwires Zigzag, epaxos/ReplicaMain.scala:127).
    dependency_graph: str = "tarjan"
    # "host": per-reply IntPrefixSet loops. "tpu": slow-path dep unions and
    # fast-path identical-deps tests as batched ops/depset.py reductions
    # (see device_deps.py).
    dep_backend: str = "host"


@dataclasses.dataclass
class Triple:
    command_or_noop: object
    sequence_number: int
    dependencies: InstancePrefixSet


# Command log entries (Replica.scala:298-336).
@dataclasses.dataclass
class NoCommandEntry:
    ballot: Ballot


@dataclasses.dataclass
class PreAcceptedEntry:
    ballot: Ballot
    vote_ballot: Ballot
    triple: Triple


@dataclasses.dataclass
class AcceptedEntry:
    ballot: Ballot
    vote_ballot: Ballot
    triple: Triple


@dataclasses.dataclass
class CommittedEntry:
    triple: Triple


CmdLogEntry = Union[NoCommandEntry, PreAcceptedEntry, AcceptedEntry,
                    CommittedEntry]


# Leader states (Replica.scala:338-388).
@dataclasses.dataclass
class PreAccepting:
    ballot: Ballot
    command_or_noop: object
    responses: dict[int, PreAcceptOk]
    avoid_fast_path: bool
    resend_timer: object
    default_slow_timer: Optional[object] = None


@dataclasses.dataclass
class Accepting:
    ballot: Ballot
    triple: Triple
    responses: dict[int, AcceptOk]
    resend_timer: object


@dataclasses.dataclass
class Preparing:
    ballot: Ballot
    responses: dict[int, PrepareOk]
    resend_timer: object


class EPaxosReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: EPaxosConfig,
                 state_machine: StateMachine,
                 options: EPaxosReplicaOptions = EPaxosReplicaOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = list(config.replica_addresses).index(address)
        self.other_addresses = [a for a in config.replica_addresses
                                if a != address]

        self.cmd_log: dict[Instance, CmdLogEntry] = {}
        self.next_available_instance = 0
        self.default_ballot: Ballot = (0, self.index)
        self.largest_ballot: Ballot = (0, self.index)
        self.leader_states: dict[Instance, object] = {}
        self.dependency_graph = make_dependency_graph(
            options.dependency_graph, num_leaders=config.n, make=Instance)
        self.client_table: ClientTable = ClientTable()
        self.conflict_index = state_machine.top_k_conflict_index(
            options.top_k_dependencies, config.n, TUPLE_VERTEX_LIKE)
        self.recover_instance_timers: dict[Instance, object] = {}
        self.num_pending_committed = 0
        self.executed_count = 0

    # --- helpers ----------------------------------------------------------
    def _leader_ballot(self, state) -> Ballot:
        return state.ballot

    def _thrifty_others(self, n: int) -> list[Address]:
        return self.other_addresses[:n]

    def _compute_seq_deps(self, instance: Instance, command_or_noop
                          ) -> tuple[int, InstancePrefixSet]:
        if isinstance(command_or_noop, Noop):
            return 0, InstancePrefixSet(self.config.n)
        payload = command_or_noop.command
        if self.options.top_k_dependencies == 1:
            deps = InstancePrefixSet.from_top_one(
                self.conflict_index.get_top_one_conflicts(payload))
        else:
            deps = InstancePrefixSet.from_top_k(
                self.conflict_index.get_top_k_conflicts(payload))
        deps.subtract_one(instance)
        # Note: with top-k conflict indexes, true EPaxos sequence numbers
        # can't be computed; they aren't needed (Replica.scala:565-568).
        return 0, deps

    def _update_conflict_index(self, instance: Instance, value) -> None:
        if isinstance(value, Command):
            self.conflict_index.put(instance, value.command)

    def _stop_timers(self, instance: Instance) -> None:
        state = self.leader_states.get(instance)
        if isinstance(state, PreAccepting):
            state.resend_timer.stop()
            if state.default_slow_timer is not None:
                state.default_slow_timer.stop()
        elif isinstance(state, Accepting):
            state.resend_timer.stop()
        elif isinstance(state, Preparing):
            state.resend_timer.stop()

    def _check_can_overwrite(self, instance: Instance, ballot: Ballot) -> None:
        entry = self.cmd_log.get(instance)
        if isinstance(entry, CommittedEntry):
            self.logger.fatal(
                f"overwriting committed instance {instance}")
        if isinstance(entry, (PreAcceptedEntry, AcceptedEntry)):
            self.logger.check_le(entry.ballot, ballot)
            self.logger.check_le(entry.vote_ballot, ballot)
        elif isinstance(entry, NoCommandEntry):
            self.logger.check_le(entry.ballot, ballot)

    def _make_repeating_timer(self, name: str, period_s: float, body) -> object:
        def fire():
            # Re-arm BEFORE the body: a body that transitions state
            # stops this timer via _stop_timers, and re-arming after it
            # would resurrect a stopped timer -- the defaultToSlowPath
            # timer then fires in the Accepting state and trips the
            # fatal check (found by the 500x250 soak,
            # tests/soak.py epaxos/f1).
            timer.start()
            body()

        timer = self.timer(name, period_s, fire)
        timer.start()
        return timer

    # --- phase transitions (Replica.scala:634-1010) -----------------------
    def _transition_to_pre_accept(self, instance: Instance, ballot: Ballot,
                                  command_or_noop, avoid_fast_path: bool
                                  ) -> None:
        sequence_number, dependencies = self._compute_seq_deps(
            instance, command_or_noop)
        self._check_can_overwrite(instance, ballot)
        self.cmd_log[instance] = PreAcceptedEntry(
            ballot=ballot, vote_ballot=ballot,
            triple=Triple(command_or_noop, sequence_number, dependencies))
        self._update_conflict_index(instance, command_or_noop)

        pre_accept = PreAccept(instance=instance, ballot=ballot,
                               command_or_noop=command_or_noop,
                               sequence_number=sequence_number,
                               dependencies=dependencies.copy())
        targets = self._thrifty_others(self.config.fast_quorum_size - 1)
        self.broadcast(targets, pre_accept)

        self._stop_timers(instance)

        def resend():
            self.broadcast(self.other_addresses, pre_accept)

        self.leader_states[instance] = PreAccepting(
            ballot=ballot,
            command_or_noop=command_or_noop,
            responses={self.index: PreAcceptOk(
                instance=instance, ballot=ballot, replica_index=self.index,
                sequence_number=sequence_number,
                dependencies=dependencies.copy())},
            avoid_fast_path=avoid_fast_path,
            resend_timer=self._make_repeating_timer(
                f"resendPreAccepts {instance}",
                self.options.resend_pre_accepts_period_s, resend),
        )

    def _transition_to_accept(self, instance: Instance, ballot: Ballot,
                              triple: Triple) -> None:
        self._check_can_overwrite(instance, ballot)
        self.cmd_log[instance] = AcceptedEntry(ballot=ballot,
                                               vote_ballot=ballot,
                                               triple=triple)
        self._update_conflict_index(instance, triple.command_or_noop)

        accept = Accept(instance=instance, ballot=ballot,
                        command_or_noop=triple.command_or_noop,
                        sequence_number=triple.sequence_number,
                        dependencies=triple.dependencies.copy())
        self.broadcast(
            self._thrifty_others(self.config.slow_quorum_size - 1),
            accept)

        self._stop_timers(instance)

        def resend():
            self.broadcast(self.other_addresses, accept)

        self.leader_states[instance] = Accepting(
            ballot=ballot, triple=triple,
            responses={self.index: AcceptOk(instance=instance, ballot=ballot,
                                            replica_index=self.index)},
            resend_timer=self._make_repeating_timer(
                f"resendAccepts {instance}",
                self.options.resend_accepts_period_s, resend),
        )

    def _pre_accepting_slow_path(self, instance: Instance,
                                 state: PreAccepting) -> None:
        """Union deps across a classic quorum (Replica.scala:795-813)."""
        self.logger.check_ge(len(state.responses),
                             self.config.slow_quorum_size)
        if self.options.dep_backend == "tpu":
            from frankenpaxos_tpu.protocols.epaxos import device_deps
            sequence_number, dependencies = device_deps.conflict_max_many(
                [(r.sequence_number, r.dependencies)
                 for r in state.responses.values()],
                self.config.n,
                metrics=self.transport.runtime_metrics)
        else:
            sequence_number = max(r.sequence_number
                                  for r in state.responses.values())
            dependencies = InstancePrefixSet(self.config.n)
            for response in state.responses.values():
                dependencies.add_all(response.dependencies)
        self._transition_to_accept(
            instance, state.ballot,
            Triple(state.command_or_noop, sequence_number, dependencies))

    def _transition_to_prepare(self, instance: Instance) -> None:
        """Explicit-prepare recovery (Replica.scala:972-1010)."""
        self._stop_timers(instance)
        self.largest_ballot = (self.largest_ballot[0] + 1, self.index)
        ballot = self.largest_ballot
        prepare = Prepare(instance=instance, ballot=ballot)
        targets = self._thrifty_others(self.config.slow_quorum_size - 1)
        self.broadcast([*targets, self.address], prepare)

        def resend():
            self.broadcast(self.config.replica_addresses, prepare)

        self.leader_states[instance] = Preparing(
            ballot=ballot, responses={},
            resend_timer=self._make_repeating_timer(
                f"resendPrepares {instance}",
                self.options.resend_prepares_period_s, resend),
        )

    # --- commit + execution (Replica.scala:815-965) -----------------------
    def _commit(self, instance: Instance, triple: Triple,
                inform_others: bool) -> None:
        if isinstance(self.cmd_log.get(instance), CommittedEntry):
            return  # duplicate Commit
        self._stop_timers(instance)
        self.cmd_log[instance] = CommittedEntry(triple)
        self._update_conflict_index(instance, triple.command_or_noop)
        self.leader_states.pop(instance, None)

        if inform_others:
            commit = Commit(instance=instance,
                            command_or_noop=triple.command_or_noop,
                            sequence_number=triple.sequence_number,
                            dependencies=triple.dependencies.copy())
            self.broadcast(self.other_addresses, commit)

        timer = self.recover_instance_timers.pop(instance, None)
        if timer is not None:
            timer.stop()

        if self.options.unsafe_skip_graph_execution:
            self._execute_command(instance, triple.command_or_noop)
            return
        self.dependency_graph.commit(instance, triple.sequence_number,
                                     triple.dependencies.materialize())
        self.num_pending_committed += 1
        if (self.num_pending_committed
                % self.options.execute_graph_batch_size == 0):
            self._execute_graph()
            self.num_pending_committed = 0

    def _execute_graph(self) -> None:
        executables, blockers = self.dependency_graph.execute(
            self.options.num_blockers)
        for blocked in blockers:
            if blocked not in self.recover_instance_timers:
                self.recover_instance_timers[blocked] = \
                    self._make_recover_timer(blocked)
        for instance in executables:
            entry = self.cmd_log.get(instance)
            if not isinstance(entry, CommittedEntry):
                self.logger.fatal(
                    f"instance {instance} executable but not committed")
            self._execute_command(instance, entry.triple.command_or_noop)

    def _make_recover_timer(self, instance: Instance) -> object:
        return self._make_repeating_timer(
            f"recoverInstance {instance}",
            self.rng.uniform(self.options.recover_instance_min_period_s,
                             self.options.recover_instance_max_period_s),
            lambda: self._transition_to_prepare(instance))

    def _execute_command(self, instance: Instance, value) -> None:
        if isinstance(value, Noop):
            return
        command: Command = value
        identity = (command.client_address, command.client_pseudonym)
        executed = self.client_table.executed(identity, command.client_id)
        if executed is not NOT_EXECUTED:
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        self.executed_count += 1
        # The instance's column owner replies (Replica.scala:946-962).
        if self.index == instance.replica_index:
            self.send(command.client_address,
                      ClientReply(client_pseudonym=command.client_pseudonym,
                                  client_id=command.client_id,
                                  result=output))

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        handlers = {
            ClientRequest: self._handle_client_request,
            PreAccept: self._handle_pre_accept,
            PreAcceptOk: self._handle_pre_accept_ok,
            Accept: self._handle_accept,
            AcceptOk: self._handle_accept_ok,
            Commit: self._handle_commit,
            Nack: self._handle_nack,
            Prepare: self._handle_prepare,
            PrepareOk: self._handle_prepare_ok,
        }
        handler = handlers.get(type(message))
        if handler is None:
            self.logger.fatal(f"unexpected epaxos message {message!r}")
        handler(src, message)

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        command = request.command
        identity = (command.client_address, command.client_pseudonym)
        executed = self.client_table.executed(identity, command.client_id)
        if isinstance(executed, Executed):
            if executed.output is not None:
                self.send(src, ClientReply(
                    client_pseudonym=command.client_pseudonym,
                    client_id=command.client_id, result=executed.output))
            return
        instance = Instance(self.index, self.next_available_instance)
        self.next_available_instance += 1
        self._transition_to_pre_accept(instance, self.default_ballot,
                                       command, avoid_fast_path=False)

    def _yield_leadership_if_preempted(self, instance: Instance,
                                       ballot: Ballot) -> None:
        state = self.leader_states.get(instance)
        if state is not None and ballot > self._leader_ballot(state):
            self._stop_timers(instance)
            del self.leader_states[instance]

    def _handle_pre_accept(self, src: Address, pre_accept: PreAccept) -> None:
        """(Replica.scala:1159-1290)."""
        instance = pre_accept.instance
        entry = self.cmd_log.get(instance)
        nack = Nack(instance, self.largest_ballot)
        if isinstance(entry, NoCommandEntry):
            # `<` not `<=`: preparing is phase 1, pre-accepting is phase 2.
            if pre_accept.ballot < entry.ballot:
                self.send(src, nack)
                return
        elif isinstance(entry, PreAcceptedEntry):
            if pre_accept.ballot < entry.ballot:
                self.send(src, nack)
                return
            if pre_accept.ballot == entry.vote_ballot:
                # Already responded; re-send for liveness.
                self.send(src, PreAcceptOk(
                    instance=instance, ballot=pre_accept.ballot,
                    replica_index=self.index,
                    sequence_number=entry.triple.sequence_number,
                    dependencies=entry.triple.dependencies.copy()))
                return
        elif isinstance(entry, AcceptedEntry):
            if pre_accept.ballot < entry.ballot:
                self.send(src, nack)
                return
            if pre_accept.ballot == entry.vote_ballot:
                return  # already accepted in this ballot
        elif isinstance(entry, CommittedEntry):
            self.send(src, Commit(
                instance=instance,
                command_or_noop=entry.triple.command_or_noop,
                sequence_number=entry.triple.sequence_number,
                dependencies=entry.triple.dependencies.copy()))
            return

        self._yield_leadership_if_preempted(instance, pre_accept.ballot)
        self.largest_ballot = max(self.largest_ballot, pre_accept.ballot)
        timer = self.recover_instance_timers.get(instance)
        if timer is not None:
            timer.reset()

        sequence_number, dependencies = self._compute_seq_deps(
            instance, pre_accept.command_or_noop)
        sequence_number = max(sequence_number, pre_accept.sequence_number)
        dependencies.add_all(pre_accept.dependencies)
        self.cmd_log[instance] = PreAcceptedEntry(
            ballot=pre_accept.ballot, vote_ballot=pre_accept.ballot,
            triple=Triple(pre_accept.command_or_noop, sequence_number,
                          dependencies))
        self._update_conflict_index(instance, pre_accept.command_or_noop)
        self.send(src, PreAcceptOk(
            instance=instance, ballot=pre_accept.ballot,
            replica_index=self.index, sequence_number=sequence_number,
            dependencies=dependencies.copy()))

    def _handle_pre_accept_ok(self, src: Address, ok: PreAcceptOk) -> None:
        """(Replica.scala:1291-1420)."""
        state = self.leader_states.get(ok.instance)
        if not isinstance(state, PreAccepting):
            self.logger.debug(f"PreAcceptOk for {ok.instance} ignored")
            return
        if ok.ballot != state.ballot:
            self.logger.check_lt(ok.ballot, state.ballot)
            return

        old_count = len(state.responses)
        state.responses[ok.replica_index] = ok
        new_count = len(state.responses)
        slow, fast = (self.config.slow_quorum_size,
                      self.config.fast_quorum_size)
        if new_count < slow:
            return
        # First classic quorum: arm the default-to-slow-path timer while
        # waiting for a full fast quorum.
        if (not state.avoid_fast_path and old_count < slow <= new_count
                and slow < fast):
            if state.default_slow_timer is None:
                state.default_slow_timer = self._make_repeating_timer(
                    f"defaultToSlowPath {ok.instance}",
                    self.options.default_to_slow_path_period_s,
                    lambda: self._default_to_slow_path(ok.instance))
            return
        if state.avoid_fast_path and new_count >= slow:
            self._pre_accepting_slow_path(ok.instance, state)
            return
        if new_count >= fast:
            # Fast path iff n-2 non-leader replies match exactly.
            seq_deps = [(r.sequence_number, r.dependencies)
                        for i, r in state.responses.items()
                        if i != self.index]
            if (self.options.dep_backend == "tpu"
                    and len(seq_deps) == fast - 1):
                # With threshold == reply count, "count >= fast-1"
                # collapses to "all replies identical" -- one batched
                # device equality over the normalized dep sets.
                from frankenpaxos_tpu.protocols.epaxos import device_deps
                winner = (seq_deps[0]
                          if device_deps.all_identical(
                              seq_deps, self.config.n,
                              metrics=self.transport.runtime_metrics)
                          else None)
            else:
                counts = _Counter(seq_deps)
                candidates = [sd for sd, c in counts.items()
                              if c >= fast - 1]
                if candidates:
                    self.logger.check_eq(len(candidates), 1)
                winner = candidates[0] if candidates else None
            if winner is not None:
                sequence_number, dependencies = winner
                self._commit(ok.instance,
                             Triple(state.command_or_noop, sequence_number,
                                    dependencies.copy()),
                             inform_others=True)
            else:
                self._pre_accepting_slow_path(ok.instance, state)

    def _default_to_slow_path(self, instance: Instance) -> None:
        state = self.leader_states.get(instance)
        if not isinstance(state, PreAccepting):
            self.logger.fatal("defaultToSlowPath fired outside PreAccepting")
        self._pre_accepting_slow_path(instance, state)

    def _handle_accept(self, src: Address, accept: Accept) -> None:
        """(Replica.scala:1421-1512)."""
        instance = accept.instance
        entry = self.cmd_log.get(instance)
        nack = Nack(instance, self.largest_ballot)
        if isinstance(entry, (NoCommandEntry, PreAcceptedEntry)):
            if accept.ballot < entry.ballot:
                self.send(src, nack)
                return
        elif isinstance(entry, AcceptedEntry):
            if accept.ballot < entry.ballot:
                self.send(src, nack)
                return
            if accept.ballot == entry.vote_ballot:
                self.send(src, AcceptOk(instance=instance,
                                        ballot=accept.ballot,
                                        replica_index=self.index))
                return
        elif isinstance(entry, CommittedEntry):
            self.send(src, Commit(
                instance=instance,
                command_or_noop=entry.triple.command_or_noop,
                sequence_number=entry.triple.sequence_number,
                dependencies=entry.triple.dependencies.copy()))
            return

        self._yield_leadership_if_preempted(instance, accept.ballot)
        self.largest_ballot = max(self.largest_ballot, accept.ballot)
        timer = self.recover_instance_timers.get(instance)
        if timer is not None:
            timer.reset()
        self.cmd_log[instance] = AcceptedEntry(
            ballot=accept.ballot, vote_ballot=accept.ballot,
            triple=Triple(accept.command_or_noop, accept.sequence_number,
                          accept.dependencies.copy()))
        self._update_conflict_index(instance, accept.command_or_noop)
        self.send(src, AcceptOk(instance=instance, ballot=accept.ballot,
                                replica_index=self.index))

    def _handle_accept_ok(self, src: Address, ok: AcceptOk) -> None:
        state = self.leader_states.get(ok.instance)
        if not isinstance(state, Accepting):
            self.logger.debug(f"AcceptOk for {ok.instance} ignored")
            return
        if ok.ballot != state.ballot:
            self.logger.check_lt(ok.ballot, state.ballot)
            return
        state.responses[ok.replica_index] = ok
        if len(state.responses) < self.config.slow_quorum_size:
            return
        self._commit(ok.instance, state.triple, inform_others=True)

    def _handle_commit(self, src: Address, commit: Commit) -> None:
        self._commit(commit.instance,
                     Triple(commit.command_or_noop, commit.sequence_number,
                            commit.dependencies.copy()),
                     inform_others=False)

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        """(Replica.scala:1577-1631): wait a random delay, then recover
        with a higher ballot (avoids dueling recoverers)."""
        self.largest_ballot = max(self.largest_ballot, nack.largest_ballot)
        state = self.leader_states.get(nack.instance)
        if state is None or state.ballot >= nack.largest_ballot:
            return
        timer = self.recover_instance_timers.get(nack.instance)
        if timer is not None:
            timer.reset()
        else:
            self.recover_instance_timers[nack.instance] = \
                self._make_recover_timer(nack.instance)

    def _handle_prepare(self, src: Address, prepare: Prepare) -> None:
        """(Replica.scala:1632-1757)."""
        instance = prepare.instance
        self.largest_ballot = max(self.largest_ballot, prepare.ballot)
        timer = self.recover_instance_timers.get(instance)
        if timer is not None:
            timer.reset()
        self._yield_leadership_if_preempted(instance, prepare.ballot)

        entry = self.cmd_log.get(instance)
        nack = Nack(instance, self.largest_ballot)
        if entry is None or isinstance(entry, NoCommandEntry):
            if entry is not None and prepare.ballot < entry.ballot:
                self.send(src, nack)
                return
            self.send(src, PrepareOk(
                ballot=prepare.ballot, instance=instance,
                replica_index=self.index, vote_ballot=NULL_BALLOT,
                status=CommandStatus.NOT_SEEN, command_or_noop=None,
                sequence_number=None, dependencies=None))
            self.cmd_log[instance] = NoCommandEntry(prepare.ballot)
        elif isinstance(entry, (PreAcceptedEntry, AcceptedEntry)):
            if prepare.ballot < entry.ballot:
                self.send(src, nack)
                return
            status = (CommandStatus.PRE_ACCEPTED
                      if isinstance(entry, PreAcceptedEntry)
                      else CommandStatus.ACCEPTED)
            self.send(src, PrepareOk(
                ballot=prepare.ballot, instance=instance,
                replica_index=self.index, vote_ballot=entry.vote_ballot,
                status=status, command_or_noop=entry.triple.command_or_noop,
                sequence_number=entry.triple.sequence_number,
                dependencies=entry.triple.dependencies.copy()))
            entry.ballot = prepare.ballot
        else:
            assert isinstance(entry, CommittedEntry)
            self.send(src, Commit(
                instance=instance,
                command_or_noop=entry.triple.command_or_noop,
                sequence_number=entry.triple.sequence_number,
                dependencies=entry.triple.dependencies.copy()))

    def _handle_prepare_ok(self, src: Address, ok: PrepareOk) -> None:
        """(Replica.scala:1759-1940)."""
        state = self.leader_states.get(ok.instance)
        if not isinstance(state, Preparing):
            self.logger.debug(f"PrepareOk for {ok.instance} ignored")
            return
        if ok.ballot != state.ballot:
            self.logger.check_lt(ok.ballot, state.ballot)
            return
        state.responses[ok.replica_index] = ok
        if len(state.responses) < self.config.slow_quorum_size:
            return

        max_vote_ballot = max(r.vote_ballot for r in state.responses.values())
        top = [r for r in state.responses.values()
               if r.vote_ballot == max_vote_ballot]

        # An Accepted vote wins outright (like a classic-round vote).
        for response in top:
            if response.status == CommandStatus.ACCEPTED:
                self._transition_to_accept(
                    ok.instance, state.ballot,
                    Triple(response.command_or_noop,
                           response.sequence_number,
                           response.dependencies.copy()))
                return

        # f matching default-ballot PreAccepts (excluding the column
        # owner) mean the fast path may have chosen it.
        matching = [
            (r.sequence_number, r.dependencies)
            for r in top
            if r.status == CommandStatus.PRE_ACCEPTED
            and r.ballot == (0, r.instance.replica_index)
            and r.replica_index != self.index
        ]
        counts = _Counter(matching)
        candidates = [sd for sd, c in counts.items() if c >= self.config.f]
        if candidates:
            self.logger.check_eq(len(candidates), 1)
            sequence_number, dependencies = candidates[0]
            pre_accepted = next(r for r in top
                                if r.status == CommandStatus.PRE_ACCEPTED)
            self._transition_to_accept(
                ok.instance, state.ballot,
                Triple(pre_accepted.command_or_noop, sequence_number,
                       dependencies.copy()))
            return

        # Otherwise restart with the seen command, or a noop.
        pre_accepted = next((r for r in top
                             if r.status == CommandStatus.PRE_ACCEPTED), None)
        if pre_accepted is not None:
            self._transition_to_pre_accept(ok.instance, state.ballot,
                                           pre_accepted.command_or_noop,
                                           avoid_fast_path=True)
        else:
            self._transition_to_pre_accept(ok.instance, state.ballot,
                                           NOOP, avoid_fast_path=True)
