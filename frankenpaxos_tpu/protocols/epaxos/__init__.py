"""EPaxos: leaderless generalized consensus.

Reference behavior: epaxos/ (~2,400 LoC Scala; SURVEY.md section 2.2).
One Replica role holding every sub-role; dependency sets as
InstancePrefixSets (per-replica watermark columns -- the device twin is
ops/depset.py); execution via Tarjan SCC ordering.
"""

from frankenpaxos_tpu.protocols.epaxos.client import EPaxosClient
from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    Instance,
    InstancePrefixSet,
)
from frankenpaxos_tpu.protocols.epaxos.replica import (
    EPaxosConfig,
    EPaxosReplica,
    EPaxosReplicaOptions,
)

__all__ = [
    "EPaxosClient",
    "EPaxosConfig",
    "EPaxosReplica",
    "EPaxosReplicaOptions",
    "Instance",
    "InstancePrefixSet",
]

# Importing registers the EPaxos binary codecs with the hybrid
# serializer (see wire.py for the layout).
from frankenpaxos_tpu.protocols.epaxos import wire  # noqa: E402,F401
