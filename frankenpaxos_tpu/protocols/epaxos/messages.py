"""EPaxos wire messages (reference: epaxos/EPaxos.proto)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Union

from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    Instance,
    InstancePrefixSet,
)
from frankenpaxos_tpu.runtime.transport import Address

# Ballots order lexicographically by (ordering, replica_index)
# (EPaxos.proto:46-52).
Ballot = tuple[int, int]
NULL_BALLOT: Ballot = (-1, -1)


@dataclasses.dataclass(frozen=True)
class Command:
    client_address: Address
    client_pseudonym: int
    client_id: int
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
CommandOrNoop = Union[Command, Noop]


class CommandStatus(enum.Enum):
    NOT_SEEN = "not_seen"
    PRE_ACCEPTED = "pre_accepted"
    ACCEPTED = "accepted"
    COMMITTED = "committed"


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class PreAccept:
    instance: Instance
    ballot: Ballot
    command_or_noop: CommandOrNoop
    sequence_number: int
    dependencies: InstancePrefixSet


@dataclasses.dataclass(frozen=True)
class PreAcceptOk:
    instance: Instance
    ballot: Ballot
    replica_index: int
    sequence_number: int
    dependencies: InstancePrefixSet


@dataclasses.dataclass(frozen=True)
class Accept:
    instance: Instance
    ballot: Ballot
    command_or_noop: CommandOrNoop
    sequence_number: int
    dependencies: InstancePrefixSet


@dataclasses.dataclass(frozen=True)
class AcceptOk:
    instance: Instance
    ballot: Ballot
    replica_index: int


@dataclasses.dataclass(frozen=True)
class Commit:
    instance: Instance
    command_or_noop: CommandOrNoop
    sequence_number: int
    dependencies: InstancePrefixSet


@dataclasses.dataclass(frozen=True)
class Nack:
    instance: Instance
    largest_ballot: Ballot


@dataclasses.dataclass(frozen=True)
class Prepare:
    instance: Instance
    ballot: Ballot


@dataclasses.dataclass(frozen=True)
class PrepareOk:
    ballot: Ballot
    instance: Instance
    replica_index: int
    vote_ballot: Ballot
    status: CommandStatus
    command_or_noop: Optional[CommandOrNoop]
    sequence_number: Optional[int]
    dependencies: Optional[InstancePrefixSet]


@dataclasses.dataclass(frozen=True)
class ClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes
