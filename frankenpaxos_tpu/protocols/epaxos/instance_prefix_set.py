"""InstancePrefixSet: a compact set of EPaxos instances.

Reference behavior: epaxos/InstancePrefixSet.scala:12-60. An EPaxos
instance is (replica_index, instance_number); a set of instances is one
IntPrefixSet per replica column. Dependency sets compact to per-replica
watermark vectors -- the host twin of the device representation in
ops/depset.py.
"""

from __future__ import annotations

from typing import Iterable, Iterator, NamedTuple

from frankenpaxos_tpu.compact import IntPrefixSet
from frankenpaxos_tpu.utils.topk import TopK, TopOne


class Instance(NamedTuple):
    replica_index: int
    instance_number: int


class InstancePrefixSet:
    def __init__(self, num_replicas: int,
                 int_prefix_sets: list[IntPrefixSet] | None = None):
        self.num_replicas = num_replicas
        self.columns = (int_prefix_sets
                        or [IntPrefixSet() for _ in range(num_replicas)])

    def __repr__(self):
        return f"InstancePrefixSet({self.columns!r})"

    def __eq__(self, other):
        return (isinstance(other, InstancePrefixSet)
                and self.columns == other.columns)

    def __hash__(self):
        return hash(tuple((c.watermark, frozenset(c.values))
                          for c in self.columns))

    @classmethod
    def from_watermarks(cls, watermarks: Iterable[int]) -> "InstancePrefixSet":
        cols = [IntPrefixSet.from_watermark(w) for w in watermarks]
        return cls(len(cols), cols)

    @classmethod
    def from_top_one(cls, top_one: TopOne) -> "InstancePrefixSet":
        return cls.from_watermarks(top_one.get())

    @classmethod
    def from_top_k(cls, top_k: TopK) -> "InstancePrefixSet":
        cols = []
        for ids in top_k.get():
            if not ids:
                cols.append(IntPrefixSet())
            else:
                # The smallest of the top-k becomes a watermark ("everything
                # up to here might conflict"); the rest stay sparse
                # (InstancePrefixSet.scala fromTopK).
                cols.append(IntPrefixSet(ids[0] + 1, ids[1:]))
        return cls(len(cols), cols)

    def add(self, instance: Instance) -> bool:
        return self.columns[instance[0]].add(instance[1])

    def contains(self, instance: Instance) -> bool:
        return self.columns[instance[0]].contains(instance[1])

    def add_all(self, other: "InstancePrefixSet") -> "InstancePrefixSet":
        for mine, theirs in zip(self.columns, other.columns):
            mine.add_all(theirs)
        return self

    def subtract_one(self, instance: Instance) -> "InstancePrefixSet":
        self.columns[instance[0]].subtract_one(instance[1])
        return self

    def materialized_diff(self, other: "InstancePrefixSet"
                          ) -> Iterator[Instance]:
        for r, (mine, theirs) in enumerate(zip(self.columns, other.columns)):
            for i in mine.materialized_diff(theirs):
                yield Instance(r, i)

    @property
    def size(self) -> int:
        return sum(c.size for c in self.columns)

    @property
    def uncompacted_size(self) -> int:
        return sum(c.uncompacted_size for c in self.columns)

    def materialize(self) -> set[Instance]:
        return {Instance(r, i)
                for r, c in enumerate(self.columns)
                for i in c.materialize()}

    def __iter__(self) -> Iterator[Instance]:
        return iter(self.materialize())

    def watermarks(self) -> list[int]:
        return [c.watermark for c in self.columns]

    def copy(self) -> "InstancePrefixSet":
        return InstancePrefixSet(
            self.num_replicas,
            [IntPrefixSet(c.watermark, set(c.values)) for c in self.columns])

    def to_dict(self) -> dict:
        return {"num_replicas": self.num_replicas,
                "columns": [c.to_dict() for c in self.columns]}

    @classmethod
    def from_dict(cls, d: dict) -> "InstancePrefixSet":
        return cls(d["num_replicas"],
                   [IntPrefixSet.from_dict(c) for c in d["columns"]])
