"""EPaxos Client.

Reference behavior: epaxos/Client.scala: per-pseudonym increasing command
ids; each command goes to a (rotating) replica with a resend timer; any
replica may answer (the column owner replies, or a resend lands at
another replica that answers from its client table).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.protocols.epaxos.messages import (
    ClientReply,
    ClientRequest,
    Command,
)
from frankenpaxos_tpu.protocols.epaxos.replica import EPaxosConfig
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend_timer: object


class EPaxosClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: EPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def propose(self, pseudonym: int, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(
                f"pseudonym {pseudonym} already has a pending command")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(self.address, pseudonym, id, command))
        replica = self.config.replica_addresses[
            self.rng.randrange(len(self.config.replica_addresses))]
        self.send(replica, request)

        def resend():
            # Resend to a (possibly different) replica.
            target = self.config.replica_addresses[
                self.rng.randrange(len(self.config.replica_addresses))]
            self.send(target, request)
            timer.start()

        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.client_pseudonym)
        if pending is None or pending.id != message.client_id:
            self.logger.debug(f"stale reply {message}")
            return
        pending.resend_timer.stop()
        del self.pending[message.client_pseudonym]
        pending.callback(message.result)
