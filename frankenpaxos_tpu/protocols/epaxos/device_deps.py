"""Device-backed dependency-set algebra for the EPaxos replica.

Bridges host ``InstancePrefixSet``s (one IntPrefixSet per replica column,
epaxos/InstancePrefixSet.scala:12-60) to the batched ``DepSetBatch`` form
of ``ops/depset.py`` so the replica's two hottest set computations run as
single device reductions per call instead of per-reply host loops:

  * slow-path dependency union across a quorum of PreAcceptOks
    (epaxos/Replica.scala:795-813) -> :func:`union_many`;
  * fast-path "all replies carry identical deps" test
    (epaxos/Replica.scala:1291-1420) -> :func:`all_identical`.

Sets whose sparse tails span more than ``MAX_TAIL_WINDOW`` ids fall back
to the host path -- the device layout is a dense window and EPaxos tails
are near the per-column watermarks in steady state, so the fallback is
the rare case, not the common one.
"""

from __future__ import annotations

import numpy as np

from frankenpaxos_tpu.compact import IntPrefixSet
from frankenpaxos_tpu.ops import depset
from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    InstancePrefixSet,
)

MAX_TAIL_WINDOW = 2048


def to_batch(sets: list[InstancePrefixSet],
             num_replicas: int) -> depset.DepSetBatch | None:
    """Pack host sets into one [B, L, W] device batch.

    Returns None when the sparse tails span a window wider than
    ``MAX_TAIL_WINDOW`` (callers fall back to host algebra).
    """
    import jax.numpy as jnp

    values = [v for s in sets for c in s.columns for v in c.values]
    base = min(values) if values else 0
    spread = (max(values) - base + 1) if values else 1
    width = 8
    while width < spread:
        width *= 2
    if width > MAX_TAIL_WINDOW:
        return None
    watermarks = np.zeros((len(sets), num_replicas), dtype=np.int32)
    tails = np.zeros((len(sets), num_replicas, width), dtype=np.uint8)
    for b, instance_set in enumerate(sets):
        for column_index, column in enumerate(instance_set.columns):
            watermarks[b, column_index] = column.watermark
            for v in column.values:
                tails[b, column_index, v - base] = 1
    return depset.DepSetBatch(jnp.asarray(watermarks), jnp.asarray(tails),
                              jnp.int32(base))


def from_row(watermarks: np.ndarray, tails: np.ndarray,
             tail_base: int) -> InstancePrefixSet:
    """Unpack one device row ([L], [L, W]) back into an InstancePrefixSet."""
    columns = []
    for column_index in range(watermarks.shape[0]):
        present = np.nonzero(tails[column_index])[0]
        columns.append(IntPrefixSet(
            int(watermarks[column_index]),
            {tail_base + int(i) for i in present}))
    return InstancePrefixSet(len(columns), columns)


def _count(metrics, nsets: int, fell_back: bool) -> None:
    """paxruns runtime metrics (obs/trace.py): dep columns routed
    through the batched engine, and sparse-span host fallbacks."""
    if metrics is None:
        return
    metrics.depset_batch(nsets)
    if fell_back:
        metrics.depset_span_fallback()


def union_many(sets: list[InstancePrefixSet],
               num_replicas: int, metrics=None) -> InstancePrefixSet:
    """Union of all sets, reduced on device (host fallback on overflow)."""
    batch = to_batch(sets, num_replicas)
    _count(metrics, len(sets), batch is None)
    if batch is None:
        union = InstancePrefixSet(num_replicas)
        for instance_set in sets:
            union.add_all(instance_set)
        return union
    reduced = depset.union_reduce(batch)
    return from_row(np.asarray(reduced.watermarks)[0],
                    np.asarray(reduced.tails)[0],
                    int(reduced.tail_base))


def conflict_max_many(seq_deps: list[tuple[int, InstancePrefixSet]],
                      num_replicas: int,
                      metrics=None) -> tuple[int, InstancePrefixSet]:
    """Quorum (max sequence number, union deps) as ONE fused device
    reduction (ops/depset.conflict_max); host fallback on overflow."""
    batch = to_batch([deps for _, deps in seq_deps], num_replicas)
    _count(metrics, len(seq_deps), batch is None)
    if batch is None:
        union = InstancePrefixSet(num_replicas)
        for _, deps in seq_deps:
            union.add_all(deps)
        return max(seq for seq, _ in seq_deps), union
    import jax.numpy as jnp

    seq, reduced = depset.conflict_max(
        jnp.asarray([seq for seq, _ in seq_deps], dtype=jnp.int32), batch)
    return int(seq), from_row(np.asarray(reduced.watermarks)[0],
                              np.asarray(reduced.tails)[0],
                              int(reduced.tail_base))


def all_identical(seq_deps: list[tuple[int, InstancePrefixSet]],
                  num_replicas: int, metrics=None) -> bool:
    """Do all (sequence number, deps) pairs denote the same set?"""
    if len(seq_deps) <= 1:
        return True
    if len({seq for seq, _ in seq_deps}) > 1:
        return False
    batch = to_batch([deps for _, deps in seq_deps], num_replicas)
    _count(metrics, len(seq_deps), batch is None)
    if batch is None:
        first = seq_deps[0][1]
        return all(deps == first for _, deps in seq_deps[1:])
    return bool(np.asarray(depset.all_equal(batch)))
