"""Binary codecs for the EPaxos hot-path messages.

The EPaxos command path (PreAccept -> PreAcceptOk -> [Accept ->
AcceptOk] -> Commit, epaxos/EPaxos.proto) carries an
``InstancePrefixSet`` on every hop; pickling those nested column
objects dominated serialization. The binary layout packs each column
as ``[i64 watermark][u32 n][n x i64 sparse values]`` -- the same
(watermark, sparse tail) factorization the device DepSetBatch uses.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.compact import IntPrefixSet
from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    Instance,
    InstancePrefixSet,
)
from frankenpaxos_tpu.protocols.epaxos.messages import (
    Accept,
    AcceptOk,
    ClientReply,
    ClientRequest,
    Command,
    CommandStatus,
    Commit,
    Nack,
    NOOP,
    Noop,
    PreAccept,
    PreAcceptOk,
    Prepare,
    PrepareOk,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
# instance (replica i32, number i64) + ballot (ordering i64, replica i32)
_HDR = struct.Struct("<iqqi")


def _put_header(out: bytearray, instance: Instance, ballot) -> None:
    out += _HDR.pack(instance.replica_index, instance.instance_number,
                     ballot[0], ballot[1])


def _take_header(buf: bytes, at: int):
    r, n, b0, b1 = _HDR.unpack_from(buf, at)
    return Instance(r, n), (b0, b1), at + _HDR.size


def _put_command_or_noop(out: bytearray, value) -> None:
    if isinstance(value, Noop):
        out.append(0)
        return
    out.append(1)
    _put_address(out, value.client_address)
    out += _I64I64.pack(value.client_pseudonym, value.client_id)
    _put_bytes(out, value.command)


def _take_command_or_noop(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return NOOP, at
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return Command(address, pseudonym, id, payload), at


def _put_deps(out: bytearray, deps: InstancePrefixSet) -> None:
    out += _I32.pack(len(deps.columns))
    for column in deps.columns:
        out += _I64.pack(column.watermark)
        out += _I32.pack(len(column.values))
        for value in column.values:
            out += _I64.pack(value)


def _take_deps(buf: bytes, at: int):
    (num_columns,) = _I32.unpack_from(buf, at)
    at += 4
    columns = []
    for _ in range(num_columns):
        (watermark,) = _I64.unpack_from(buf, at)
        (n,) = _I32.unpack_from(buf, at + 8)
        at += 12
        values = set()
        for _ in range(n):
            (v,) = _I64.unpack_from(buf, at)
            values.add(v)
            at += 8
        columns.append(IntPrefixSet(watermark, values))
    return InstancePrefixSet(num_columns, columns), at


class _PhaseCodec(MessageCodec):
    """Shared layout for PreAccept/Accept/Commit (header + command +
    seq + deps) and their Oks (header + replica + seq + deps)."""

    has_command = True

    def encode(self, out, message):
        _put_header(out, message.instance, message.ballot)
        if self.has_command:
            _put_command_or_noop(out, message.command_or_noop)
        else:
            out += _I32.pack(message.replica_index)
        out += _I64.pack(message.sequence_number)
        _put_deps(out, message.dependencies)

    def decode(self, buf, at):
        instance, ballot, at = _take_header(buf, at)
        if self.has_command:
            value, at = _take_command_or_noop(buf, at)
        else:
            (replica,) = _I32.unpack_from(buf, at)
            at += 4
        (seq,) = _I64.unpack_from(buf, at)
        deps, at = _take_deps(buf, at + 8)
        if self.has_command:
            return self.message_type(
                instance=instance, ballot=ballot, command_or_noop=value,
                sequence_number=seq, dependencies=deps), at
        return self.message_type(
            instance=instance, ballot=ballot, replica_index=replica,
            sequence_number=seq, dependencies=deps), at


class PreAcceptCodec(_PhaseCodec):
    message_type = PreAccept
    tag = 14


class PreAcceptOkCodec(_PhaseCodec):
    message_type = PreAcceptOk
    tag = 15
    has_command = False


class AcceptCodec(_PhaseCodec):
    message_type = Accept
    tag = 16


class AcceptOkCodec(MessageCodec):
    message_type = AcceptOk
    tag = 20

    def encode(self, out, message):
        _put_header(out, message.instance, message.ballot)
        out += _I32.pack(message.replica_index)

    def decode(self, buf, at):
        instance, ballot, at = _take_header(buf, at)
        (replica,) = _I32.unpack_from(buf, at)
        return AcceptOk(instance=instance, ballot=ballot,
                        replica_index=replica), at + 4


class CommitCodec(MessageCodec):
    """Commit carries no ballot (EPaxos.proto Commit)."""

    message_type = Commit
    tag = 17

    def encode(self, out, message):
        instance = message.instance
        out += _I32.pack(instance.replica_index)
        out += _I64.pack(instance.instance_number)
        _put_command_or_noop(out, message.command_or_noop)
        out += _I64.pack(message.sequence_number)
        _put_deps(out, message.dependencies)

    def decode(self, buf, at):
        (replica,) = _I32.unpack_from(buf, at)
        (number,) = _I64.unpack_from(buf, at + 4)
        value, at = _take_command_or_noop(buf, at + 12)
        (seq,) = _I64.unpack_from(buf, at)
        deps, at = _take_deps(buf, at + 8)
        return Commit(instance=Instance(replica, number),
                      command_or_noop=value, sequence_number=seq,
                      dependencies=deps), at


class EPaxosClientRequestCodec(MessageCodec):
    message_type = ClientRequest
    tag = 18

    def encode(self, out, message):
        _put_command_or_noop(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command_or_noop(buf, at)
        return ClientRequest(command), at


class EPaxosClientReplyCodec(MessageCodec):
    message_type = ClientReply
    tag = 19

    def encode(self, out, message):
        out += _I64I64.pack(message.client_pseudonym, message.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return ClientReply(pseudonym, id, result), at


# --- the recovery cold path (COD301 burn-down, extended tags 173-175) -------

_STATUS_CODES = {
    CommandStatus.NOT_SEEN: 0,
    CommandStatus.PRE_ACCEPTED: 1,
    CommandStatus.ACCEPTED: 2,
    CommandStatus.COMMITTED: 3,
}
_STATUS_BY_CODE = {v: k for k, v in _STATUS_CODES.items()}


class PrepareCodec(MessageCodec):
    message_type = Prepare
    tag = 173

    def encode(self, out, message):
        _put_header(out, message.instance, message.ballot)

    def decode(self, buf, at):
        instance, ballot, at = _take_header(buf, at)
        return Prepare(instance=instance, ballot=ballot), at


class EPaxosNackCodec(MessageCodec):
    message_type = Nack
    tag = 174

    def encode(self, out, message):
        _put_header(out, message.instance, message.largest_ballot)

    def decode(self, buf, at):
        instance, ballot, at = _take_header(buf, at)
        return Nack(instance=instance, largest_ballot=ballot), at


class PrepareOkCodec(MessageCodec):
    """header + replica + vote ballot + status byte + optional
    (command, seq, deps) -- absent exactly when the acceptor had
    NOT_SEEN state (the reply's Optionals)."""

    message_type = PrepareOk
    tag = 175

    def encode(self, out, message):
        _put_header(out, message.instance, message.ballot)
        out += _I32.pack(message.replica_index)
        out += _I64.pack(message.vote_ballot[0])
        out += _I32.pack(message.vote_ballot[1])
        out.append(_STATUS_CODES[message.status])
        if message.command_or_noop is None:
            out.append(0)
            return
        out.append(1)
        _put_command_or_noop(out, message.command_or_noop)
        out += _I64.pack(message.sequence_number)
        _put_deps(out, message.dependencies)

    def decode(self, buf, at):
        instance, ballot, at = _take_header(buf, at)
        (replica,) = _I32.unpack_from(buf, at)
        (b0,) = _I64.unpack_from(buf, at + 4)
        (b1,) = _I32.unpack_from(buf, at + 12)
        at += 16
        status = _STATUS_BY_CODE.get(buf[at])
        if status is None:
            raise ValueError(f"unknown PrepareOk status {buf[at]}")
        present = buf[at + 1]
        at += 2
        if not present:
            return PrepareOk(ballot=ballot, instance=instance,
                             replica_index=replica,
                             vote_ballot=(b0, b1), status=status,
                             command_or_noop=None,
                             sequence_number=None,
                             dependencies=None), at
        value, at = _take_command_or_noop(buf, at)
        (seq,) = _I64.unpack_from(buf, at)
        deps, at = _take_deps(buf, at + 8)
        return PrepareOk(ballot=ballot, instance=instance,
                         replica_index=replica, vote_ballot=(b0, b1),
                         status=status, command_or_noop=value,
                         sequence_number=seq, dependencies=deps), at


for _codec in (PreAcceptCodec(), PreAcceptOkCodec(), AcceptCodec(),
               AcceptOkCodec(), CommitCodec(),
               EPaxosClientRequestCodec(), EPaxosClientReplyCodec(),
               PrepareCodec(), EPaxosNackCodec(), PrepareOkCodec()):
    register_codec(_codec)

# Importing for side effect: registers the drain-coalesced
# PreAcceptOkRun codec and its paxwire coalescer for tag 15.
from frankenpaxos_tpu.runs import wire as _run_wire  # noqa: E402,F401
