"""Binary codecs for the VanillaMencius steady-state path."""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import vanillamencius as vm
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")

# --- VanillaMencius ---------------------------------------------------------


def _vm_put_command(out: bytearray, command: vm.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _vm_take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return vm.Command(vm.CommandId(address, pseudonym, id), payload), at


def _vm_put_value(out: bytearray, value) -> None:
    if isinstance(value, vm.Noop):
        out.append(0)
    else:
        out.append(1)
        _vm_put_command(out, value)


def _vm_take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return vm.NOOP, at
    return _vm_take_command(buf, at)


class VMClientRequestCodec(MessageCodec):
    message_type = vm.ClientRequest
    tag = 58

    def encode(self, out, message):
        _vm_put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _vm_take_command(buf, at)
        return vm.ClientRequest(command), at


class VMPhase2aCodec(MessageCodec):
    message_type = vm.Phase2a
    tag = 59

    def encode(self, out, message):
        out += _QQQ.pack(message.sending_server, message.slot,
                         message.round)
        _vm_put_value(out, message.value)

    def decode(self, buf, at):
        server, slot, round = _QQQ.unpack_from(buf, at)
        value, at = _vm_take_value(buf, at + _QQQ.size)
        return vm.Phase2a(sending_server=server, slot=slot, round=round,
                          value=value), at


class VMSkipCodec(MessageCodec):
    message_type = vm.Skip
    tag = 60

    def encode(self, out, message):
        out += _QQQ.pack(message.server_index,
                         message.start_slot_inclusive,
                         message.stop_slot_exclusive)

    def decode(self, buf, at):
        server, start, stop = _QQQ.unpack_from(buf, at)
        return vm.Skip(server_index=server, start_slot_inclusive=start,
                       stop_slot_exclusive=stop), at + _QQQ.size


class VMPhase2bCodec(MessageCodec):
    message_type = vm.Phase2b
    tag = 61

    def encode(self, out, message):
        out += _QQQ.pack(message.server_index, message.slot,
                         message.round)

    def decode(self, buf, at):
        server, slot, round = _QQQ.unpack_from(buf, at)
        return vm.Phase2b(server_index=server, slot=slot,
                          round=round), at + _QQQ.size


class VMChosenCodec(MessageCodec):
    message_type = vm.Chosen
    tag = 62

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        out.append(1 if message.is_revocation else 0)
        _vm_put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        is_revocation = bool(buf[at + 8])
        value, at = _vm_take_value(buf, at + 9)
        return vm.Chosen(slot=slot, value=value,
                         is_revocation=is_revocation), at


class VMClientReplyCodec(MessageCodec):
    message_type = vm.ClientReply
    tag = 63

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return vm.ClientReply(vm.CommandId(address, pseudonym, id),
                              result), at



class VMPhase1NackCodec(MessageCodec):
    """Revocation-race feedback (COD301 burn-down, paxwire extended tag
    page): per-revocation rather than per-command, but revocation
    storms ride the same congested wire as the commands that caused
    them."""

    message_type = vm.Phase1Nack
    tag = 158

    def encode(self, out, message):
        out += _QQQ.pack(message.start_slot_inclusive,
                         message.stop_slot_exclusive, message.round)

    def decode(self, buf, at):
        start, stop, round = _QQQ.unpack_from(buf, at)
        return vm.Phase1Nack(start_slot_inclusive=start,
                             stop_slot_exclusive=stop,
                             round=round), at + _QQQ.size


class VMPhase2NackCodec(MessageCodec):
    message_type = vm.Phase2Nack
    tag = 159

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        return vm.Phase2Nack(slot=slot, round=round), at + 16


for _codec in (VMClientRequestCodec(), VMPhase2aCodec(), VMSkipCodec(),
               VMPhase2bCodec(), VMChosenCodec(), VMClientReplyCodec(),
               VMPhase1NackCodec(), VMPhase2NackCodec()):
    register_codec(_codec)
