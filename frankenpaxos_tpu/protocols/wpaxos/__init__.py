"""WPaxos: wide-area per-object multi-leader Paxos (paxgeo).

Per-object leader placement across zones with asymmetric flexible
grid quorums (WPaxos, arxiv 1703.08905; quorum relaxation licensed by
Flexible Paxos, arxiv 1608.06696): commands partition by object into
groups, each group's leader lives in the object's home zone and
commits through a zone-local ``ZoneGrid`` row, and moving an object is
an epoch change (``geo.ObjectEpochStore``) committed by a cross-zone
Phase1 at f+1 WAL-durable old-home acks -- the paxepoch recipe, so
steals inherit WAL durability and watermark-bounded handover for
free. See docs/GEO.md.
"""

from frankenpaxos_tpu.protocols.wpaxos import wire  # noqa: F401  - registers codecs
from frankenpaxos_tpu.protocols.wpaxos.acceptor import WPaxosAcceptor
from frankenpaxos_tpu.protocols.wpaxos.client import (
    WPaxosClient,
    WPaxosClientOptions,
)
from frankenpaxos_tpu.protocols.wpaxos.config import WPaxosConfig
from frankenpaxos_tpu.protocols.wpaxos.leader import (
    WPaxosLeader,
    WPaxosLeaderOptions,
)
from frankenpaxos_tpu.protocols.wpaxos.replica import WPaxosReplica

__all__ = [
    "WPaxosAcceptor",
    "WPaxosClient",
    "WPaxosClientOptions",
    "WPaxosConfig",
    "WPaxosLeader",
    "WPaxosLeaderOptions",
    "WPaxosReplica",
]
