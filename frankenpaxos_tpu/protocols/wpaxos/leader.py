"""WPaxos Leader: one per zone, owning a subset of the object groups.

Steady state (the latency win the whole subsystem exists for): a
client in the home zone sends WRequest -> the leader assigns the next
slot in the group's log and Phase2a's its OWN ZONE'S acceptor row ->
a row majority acks -> chosen. Nothing crosses a zone boundary.

An object STEAL is a paxepoch-flavored epoch change (docs/GEO.md):

  stealer --WPhase1a(group, ballot, epoch)--> every acceptor
  acceptor: WAL the promise, THEN --WPhase1b--> stealer (group commit)
  stealer: read quorum (a majority of EVERY row -- which contains a
           row-majority of the old home zone: the f+1 old-epoch
           durable acks) => epoch COMMITTED; adopt in-flight votes,
           set start_slot to the chosen watermark (the handover
           bound), re-propose the unchosen tail under the new ballot,
           broadcast WEpochCommit until a read quorum of acceptors
           acked it durably.

Vote counting is drain-granular through ``geo.GeoQuorumTracker``: the
dict oracle or one fused ``EpochSegmentedChecker`` dispatch per drain,
with each slot's quorum plane selected by its steal epoch.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from frankenpaxos_tpu.geo.epochs import GeoEpoch, ObjectEpochStore
from frankenpaxos_tpu.geo.quorum import GeoQuorumTracker
from frankenpaxos_tpu.protocols.wpaxos.config import WPaxosConfig
from frankenpaxos_tpu.protocols.wpaxos.messages import (
    Command,
    CommandBatch,
    NOOP,
    Steal,
    WChosen,
    WEpochAck,
    WEpochCommit,
    WNack,
    WNotOwner,
    WPhase1a,
    WPhase1b,
    WPhase2a,
    WPhase2b,
    WRecover,
    WReply,
    WRequest,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class WPaxosLeaderOptions:
    resend_phase1a_period_s: float = 1.0
    resend_epoch_commit_period_s: float = 1.0
    #: Base delay before RETRYING a nacked steal at an escalated
    #: ballot (randomized +-50% per leader). Immediate re-escalation
    #: turns two leaders racing for one group into a ballot duel at
    #: network speed -- the classic dueling-proposers livelock, seen
    #: as a stalled deployed smoke on a contended host.
    steal_backoff_s: float = 0.25
    # paxchaos adaptive placement: per-group request-origin EWMA on
    # the OWNING leader, evaluated on a timer. When a REMOTE zone's
    # share of a group's traffic stays above ``placement_dominance``
    # for ``placement_hysteresis_checks`` consecutive checks AND the
    # group has been owned at least ``placement_min_dwell_s``, the
    # owner hands the group off (sends the dominant zone's leader a
    # Steal trigger). Hysteresis + min-dwell are what make the PR 13
    # boomerang (instant re-steal wars) unconstructible: a freshly
    # moved group cannot move again until it has both dwelled and
    # re-proven a different dominant origin. 0 (the default) disables
    # the whole policy -- no timer, no counters, no hot-path cost
    # beyond one None test per owned-group request.
    placement_check_period_s: float = 0.0
    placement_ewma_alpha: float = 0.5
    placement_dominance: float = 0.6
    placement_min_dwell_s: float = 1.0
    placement_hysteresis_checks: int = 2
    placement_min_samples: int = 4
    quorum_backend: str = "dict"     # "dict" oracle | "tpu" fused
    tpu_window: int = 4096
    recover_reply_limit: int = 256
    # paxload admission control (serve/admission.py): flat knobs so
    # the CLI's --options.admission_* overrides reach them. All-zero =
    # no controller; the admission-off hot path is one None test.
    admission_token_rate: float = 0.0
    admission_token_burst: float = 0.0
    admission_inflight_limit: int = 0
    admission_inbox_capacity: int = 0
    admission_inbox_policy: str = "reject"
    admission_codel_target_s: float = 0.0
    admission_codel_interval_s: float = 0.1
    admission_retry_after_ms: int = 0

    def admission_options(self):
        from frankenpaxos_tpu.serve.admission import options_from_flat

        return options_from_flat(self)


@dataclasses.dataclass
class _Group:
    """Leadership state for one OWNED (active) group."""

    ballot: int
    next_slot: int
    # slot -> (value, client address | None, CommandId | None)
    proposals: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _Steal:
    ballot: int
    epoch: int
    phase1bs: dict = dataclasses.field(default_factory=dict)
    buffered: list = dataclasses.field(default_factory=list)
    started_at: float = 0.0


class WPaxosLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: WPaxosConfig,
                 options: WPaxosLeaderOptions = WPaxosLeaderOptions()):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.zone = config.leader_addresses.index(address)
        self.grid = config.grid()
        self._read_spec = self.grid.read_spec()
        self._acceptor_ids = {
            addr: config.acceptor_id(zone, i)
            for zone, row in enumerate(config.acceptor_addresses)
            for i, addr in enumerate(row)}
        self.epochs = ObjectEpochStore(config.num_groups,
                                       config.initial_home)
        self.trackers = [
            GeoQuorumTracker(self.epochs, g, self.grid,
                             backend=options.quorum_backend,
                             window=options.tpu_window)
            for g in range(config.num_groups)]
        # Groups this leader currently owns and may propose in.
        # ALWAYS acquired through a steal (even a group whose initial
        # home is this zone -- the first request triggers a self-steal
        # at a fresh ballot): a leader that crashed and restarted
        # amnesiac can therefore never reuse a ballot it already
        # proposed under, which is what makes leaders safely
        # WAL-free. Epoch-0 entries are routing hints only.
        self.active: dict[int, _Group] = {}
        self.stealing: dict[int, _Steal] = {}
        # Per-group chosen log + contiguous chosen watermark. Kept for
        # the leader's tenure AND after losing ownership (replicas
        # recover holes from any leader that remembers the value).
        self.chosen: list[dict] = [dict()
                                   for _ in range(config.num_groups)]
        self.chosen_watermark: list[int] = [0] * config.num_groups
        # Duplicate suppression: (group, client, pseudonym) ->
        # [max client_id seen, cached result or None, slot].
        self._dedup: dict = {}
        # Highest ballot ever refused to us per group (nack floor).
        self._ballot_floor: dict[int, int] = {}
        self._dirty: set[int] = set()
        # WChosen/WReply staged during the current handler/drain;
        # shipped as ONE transport batch per destination (paxwire:
        # one writev, coalesced batch frames) by _flush_chosen.
        self._chosen_outbox: list = []
        self._reply_outbox: list = []
        # Steal telemetry for bench/geo_lt.py: group -> dict with
        # virtual timestamps (started/active/first_commit).
        self.steal_events: list[dict] = []
        self._open_steal_events: dict[int, dict] = {}
        # Virtual clock when the transport has one, wall clock
        # otherwise (steal telemetry AND the admission controller's
        # token bucket both need a clock that actually advances).
        if hasattr(transport, "now"):
            self._clock = lambda: transport.now
        else:
            import time

            self._clock = time.monotonic
        # String-seeded (sha512 -- deterministic across processes) so
        # sims replay identically; only the steal-retry jitter draws
        # from it.
        self._rng = random.Random(f"wpaxos-leader|{self.zone}")
        self._phase1_timers: dict[int, object] = {}
        self._steal_retry_timers: dict[int, object] = {}
        # paxchaos adaptive placement (armed only by the knob -- the
        # unarmed path carries one None test per owned-group request).
        self._placement = None
        if options.placement_check_period_s > 0:
            self._placement = {
                "counts": {},    # group -> {origin zone: ewma weight}
                "streak": {},    # group -> [dominant zone, checks]
                "acquired": {},  # group -> clock() at activation
            }
            #: Completed hand-offs, for the scenario telemetry:
            #: dicts of group / to_zone / t_s / share.
            self.placement_handoffs: list = []
            timer = self.timer("placementCheck",
                               options.placement_check_period_s,
                               self._placement_check)
            self._placement_timer = timer
            timer.start()
        # group -> (timer, entry, set of acked acceptor ids)
        self._epoch_resends: dict[int, tuple] = {}
        # paxload admission (serve/): built only when a knob arms it.
        self._rejected_exported = 0
        admission_options = options.admission_options()
        if admission_options is not None:
            from frankenpaxos_tpu.serve.admission import (
                AdmissionController,
            )

            self.admission = AdmissionController(
                admission_options, role=f"wpaxos_leader_{self.zone}",
                clock=self._clock,
                metrics=transport.runtime_metrics)
            transport.note_admission(address, self)

    # --- handlers -----------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, WPhase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, WRequest):
            self._handle_request(src, message)
        elif isinstance(message, WPhase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, WNack):
            self._handle_nack(src, message)
        elif isinstance(message, WEpochCommit):
            self._handle_epoch_commit(src, message)
        elif isinstance(message, WEpochAck):
            self._handle_epoch_ack(src, message)
        elif isinstance(message, WRecover):
            self._handle_recover(src, message)
        elif isinstance(message, Steal):
            self.steal(message.group)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    # --- the client path ----------------------------------------------------
    def _handle_request(self, src: Address, m: WRequest) -> None:
        group = m.group
        if not 0 <= group < self.config.num_groups:
            return
        if group in self.active:
            if self._placement is not None and m.origin_zone >= 0:
                counts = self._placement["counts"].setdefault(group, {})
                counts[m.origin_zone] = counts.get(m.origin_zone, 0.0) \
                    + 1.0
            self._admit_and_propose(src, m)
            return
        steal = self.stealing.get(group)
        if steal is not None:
            steal.buffered.append((src, m))
            return
        entry = self.epochs.current(group)
        if m.steal or entry.home_zone == self.zone:
            floor = self._ballot_floor.get(group, -1)
            if not m.steal and floor > entry.ballot \
                    and self.config.ballot_zone(floor) != self.zone:
                # Our epoch store says this is our home group, but we
                # have already been NACKED at a higher ballot whose
                # zone-partitioned number names another zone's leader:
                # a steal is in flight (or committed) and its
                # WEpochCommit just has not reached us yet. Redirect
                # the client there instead of stealing our old home
                # straight back -- the boomerang re-steal otherwise
                # turns every planned migration into a ballot war
                # (follow-the-sun found this: the sun could never set
                # on a zone with any residual traffic). The hint is
                # routing advice only; if the preemptor is actually
                # dead, the client's failover budget comes back with
                # steal=True, which bypasses this branch.
                self.send(src, WNotOwner(
                    group=group, command_id=m.command.command_id,
                    home_zone=self.config.ballot_zone(floor),
                    ballot=floor))
                return
            # Failover resend (the client gave up on the home zone),
            # or our own un-acquired home group (bootstrap, or an
            # amnesiac restart): acquire it with a fresh-ballot steal.
            self.steal(group, buffered=(src, m))
            return
        self.send(src, WNotOwner(
            group=group, command_id=m.command.command_id,
            home_zone=entry.home_zone, ballot=entry.ballot))

    def _admit_and_propose(self, src: Address, m: WRequest) -> None:
        cid = m.command.command_id
        key = (m.group, cid.client_address, cid.client_pseudonym)
        entry = self._dedup.get(key)
        if entry is not None and cid.client_id < entry[0]:
            return  # superseded: the client has moved on
        if entry is not None and cid.client_id == entry[0]:
            if entry[1] is not None:
                self.send(src, WReply(command_id=cid, group=m.group,
                                      slot=entry[2], result=entry[1]))
            elif entry[2] in self.active[m.group].proposals:
                # In flight: the client's resend doubles as our
                # Phase2a retransmit (no per-slot leader timer).
                value, _, _ = self.active[m.group].proposals[entry[2]]
                self._send_phase2a(m.group, entry[2], value)
            return
        if self.admission is not None and not self.admission.admit():
            from frankenpaxos_tpu.serve.messages import Rejected

            self.send(src, Rejected(
                entries=((cid.client_pseudonym, cid.client_id),),
                retry_after_ms=self.admission.retry_after_ms(),
                reason=self.admission.last_reason))
            return
        self._propose(m.group, m.command, src)

    def _propose(self, group: int, command: Command,
                 client: Optional[Address]) -> None:
        st = self.active[group]
        slot = st.next_slot
        st.next_slot += 1
        value = CommandBatch((command,))
        st.proposals[slot] = (value, client, command.command_id)
        cid = command.command_id
        self._dedup[(group, cid.client_address,
                     cid.client_pseudonym)] = [cid.client_id, None, slot]
        self._send_phase2a(group, slot, value)

    def _send_phase2a(self, group: int, slot: int, value) -> None:
        """Fan a proposal to the row governing ``slot`` -- the HOME
        row in steady state, an older epoch's row for handover-gap
        recovery (slots below the new epoch's start stay under the
        old plane, so their quorum lives in the old home zone)."""
        entry = self.epochs.epoch_of_slot(group, slot)
        st = self.active[group]
        self.broadcast(self.config.row_addresses(entry.home_zone),
                       WPhase2a(group=group, slot=slot,
                                ballot=st.ballot, value=value))

    # --- vote counting (drain-granular) -------------------------------------
    def _handle_phase2b(self, src: Address, m: WPhase2b) -> None:
        self.trackers[m.group].record(m.slot, m.ballot, m.acceptor)
        self._dirty.add(m.group)

    def on_drain(self) -> None:
        commits = 0
        for group in sorted(self._dirty):
            self._dirty.discard(group)
            newly = self.trackers[group].drain()
            if not newly:
                continue
            st = self.active.get(group)
            for slot, ballot in newly:
                if st is None or ballot != st.ballot:
                    continue  # a stale tenure's quorum
                proposal = st.proposals.pop(slot, None)
                if proposal is None:
                    continue
                value, client, cid = proposal
                commits += 1
                self._record_chosen(group, slot, value)
                if client is not None:
                    result = value.commands[0].command \
                        if isinstance(value, CommandBatch) else b""
                    self._reply_outbox.append(
                        (client, WReply(command_id=cid, group=group,
                                        slot=slot, result=result)))
                    key = (group, cid.client_address,
                           cid.client_pseudonym)
                    entry = self._dedup.get(key)
                    if entry is not None and entry[0] == cid.client_id:
                        entry[1] = result
                        entry[2] = slot
            event = self._open_steal_events.get(group)
            if event is not None and "first_commit_s" not in event:
                event["first_commit_s"] = self._clock()
                if "active_s" in event:
                    self._close_steal_event(group)
        self._flush_chosen()
        # paxworld: resync the admission in-flight measure where it
        # CHANGES -- quorums landing this drain popped proposals (and
        # steals/releases moved whole groups). Admit()'s increments
        # accrue between drains; without this resync the slot budget
        # saturates after inflight_limit admits and the leader
        # rejects forever (the PR 6 multipaxos bug class, found here
        # by the scenario matrix's goodput floor).
        if self.admission is not None:
            self.admission.set_inflight(
                sum(len(st.proposals)
                    for st in self.active.values()))
        # paxworld per-region serving health (Grafana "Global
        # serving" band): commits this drain and the running
        # rejected/shed delta, labeled with this leader's zone.
        metrics = self.transport.runtime_metrics
        if metrics is not None:
            region = self.config.zones[self.zone]
            if commits:
                metrics.region_goodput(region, commits)
            if self.admission is not None:
                total = sum(self.admission.rejected.values())
                delta = total - self._rejected_exported
                if delta:
                    metrics.region_shed(region, delta)
                    self._rejected_exported = total

    def _record_chosen(self, group: int, slot: int, value) -> None:
        self.chosen[group][slot] = value
        self._chosen_outbox.append(WChosen(group=group, slot=slot,
                                           value=value))
        wm = self.chosen_watermark[group]
        released = []
        while wm in self.chosen[group]:
            released.append(wm)
            wm += 1
        if released:
            self.chosen_watermark[group] = wm
            self.trackers[group].release(released)

    def _flush_chosen(self) -> None:
        if self._chosen_outbox:
            messages, self._chosen_outbox = self._chosen_outbox, []
            for replica in self.config.replica_addresses:
                self.send_batch(replica, messages)
        if self._reply_outbox:
            replies, self._reply_outbox = self._reply_outbox, []
            per_client: dict = {}
            for client, reply in replies:
                per_client.setdefault(client, []).append(reply)
            for client, messages in per_client.items():
                self.send_batch(client, messages)

    # --- stealing -----------------------------------------------------------
    def steal(self, group: int, buffered: Optional[tuple] = None) -> None:
        """Begin (or join) a steal of ``group`` to this zone."""
        if group in self.active:
            if buffered is not None:
                self._admit_and_propose(buffered[0], buffered[1])
            return
        st = self.stealing.get(group)
        if st is not None:
            if buffered is not None:
                st.buffered.append(buffered)
            return
        floor = max(self.epochs.max_ballot(group),
                    self._ballot_floor.get(group, -1))
        ballot = self.config.next_ballot(self.zone, floor)
        st = _Steal(ballot=ballot,
                    epoch=self.epochs.current(group).epoch + 1,
                    started_at=self._clock())
        if buffered is not None:
            st.buffered.append(buffered)
        self.stealing[group] = st
        self._open_steal_events[group] = {
            "group": group,
            "from_zone": self.epochs.current(group).home_zone,
            "to_zone": self.zone,
            "started_s": st.started_at,
        }
        self._broadcast_phase1a(group)
        timer = self._phase1_timers.get(group)
        if timer is None:
            timer = self.timer(
                f"resendPhase1a-{group}",
                self.options.resend_phase1a_period_s,
                lambda g=group: self._resend_phase1a(g))
            self._phase1_timers[group] = timer
        timer.start()

    def _broadcast_phase1a(self, group: int) -> None:
        st = self.stealing[group]
        self.broadcast(self.config.all_acceptors(),
                       WPhase1a(group=group, ballot=st.ballot,
                                epoch=st.epoch))

    def _resend_phase1a(self, group: int) -> None:
        if group in self.stealing:
            self._broadcast_phase1a(group)
            self._phase1_timers[group].start()

    def _handle_phase1b(self, src: Address, m: WPhase1b) -> None:
        st = self.stealing.get(m.group)
        if st is None or m.ballot != st.ballot:
            return
        st.phase1bs[m.acceptor] = m
        for entry in m.epochs:
            if self.epochs.offer(entry) in ("new", "replaced"):
                self.trackers[m.group].note_epochs()
        if self._read_spec.check(st.phase1bs.keys()):
            self._complete_steal(m.group)

    def _complete_steal(self, group: int) -> None:
        st = self.stealing.pop(group)
        timer = self._phase1_timers.get(group)
        if timer is not None:
            timer.stop()
        # Adopt: per slot, the highest-ballot vote; and prove chosen-ness
        # where a row majority voted one (slot, ballot) -- those values
        # are already decided and need no re-proposal.
        adopted: dict[int, tuple] = {}      # slot -> (ballot, value)
        voters: dict[tuple, set] = {}       # (slot, ballot) -> ids
        for acceptor_id, phase1b in st.phase1bs.items():
            for vote in phase1b.votes:
                best = adopted.get(vote.slot)
                if best is None or vote.ballot > best[0]:
                    adopted[vote.slot] = (vote.ballot, vote.value)
                voters.setdefault((vote.slot, vote.ballot),
                                  set()).add(acceptor_id)
        for (slot, ballot), ids in voters.items():
            if slot in self.chosen[group]:
                continue
            plane = self.epochs.epoch_of_slot(group, slot)
            if self.grid.home_write_spec(plane.home_zone).check(ids):
                self._record_chosen(group, slot, adopted[slot][1])
        # The watermark-bounded handover: the new epoch opens at the
        # first slot not known chosen; everything below stays with the
        # old era's history.
        start_slot = max(self.chosen_watermark[group],
                         self.epochs.current(group).start_slot)
        entry = GeoEpoch(group=group, epoch=st.epoch,
                         start_slot=start_slot, home_zone=self.zone,
                         ballot=st.ballot)
        verdict = self.epochs.offer(entry)
        if verdict not in ("new", "replaced"):
            # A higher-ballot steal won while we gathered acks; its
            # WEpochCommit (or our next nack) routes clients there.
            self._open_steal_events.pop(group, None)
            return
        self.trackers[group].note_epochs()
        max_voted = max(adopted, default=start_slot - 1)
        state = _Group(ballot=st.ballot,
                       next_slot=max(start_slot, max_voted + 1))
        self.active[group] = state
        # Recover the unchosen tail: adopted values (or noops for
        # holes) re-proposed under OUR ballot. Slots >= start_slot
        # count under the new home plane; the handover gap below it
        # stays under its old plane (and row) by _send_phase2a.
        for slot in range(min([start_slot] + list(adopted)),
                          state.next_slot):
            if slot in self.chosen[group] \
                    or slot in state.proposals:
                continue
            vote = adopted.get(slot)
            value = vote[1] if vote is not None else NOOP
            state.proposals[slot] = (value, None, None)
            self._send_phase2a(group, slot, value)
        event = self._open_steal_events.get(group)
        if event is not None:
            event["active_s"] = self._clock()
            event["epoch"] = st.epoch
            event["start_slot"] = start_slot
            if not state.proposals and "first_commit_s" not in event:
                # Nothing to recover: the steal is fully live now.
                event["first_commit_s"] = event["active_s"]
            if "first_commit_s" in event:
                self._close_steal_event(group)
        # Commit the epoch entry durably at the acceptors (resent
        # until a read quorum acked -- any future Phase1 then
        # discovers it) and tell the other leaders for routing.
        self._epoch_resends[group] = (
            self._epoch_timer(group), entry, set())
        self._broadcast_epoch_commit(group)
        self._epoch_resends[group][0].start()
        if self._placement is not None:
            # A freshly acquired group starts a clean dwell window
            # with no inherited traffic history.
            self._placement["acquired"][group] = self._clock()
            self._placement["counts"].pop(group, None)
            self._placement["streak"].pop(group, None)
        for src, request in st.buffered:
            self._admit_and_propose(src, request)

    def _close_steal_event(self, group: int) -> None:
        event = self._open_steal_events.pop(group, None)
        if event is not None:
            self.steal_events.append(event)

    def _epoch_timer(self, group: int):
        existing = self._epoch_resends.get(group)
        if existing is not None:
            existing[0].stop()
            return existing[0]
        return self.timer(
            f"resendEpochCommit-{group}",
            self.options.resend_epoch_commit_period_s,
            lambda g=group: self._resend_epoch_commit(g))

    def _broadcast_epoch_commit(self, group: int) -> None:
        _, entry, acked = self._epoch_resends[group]
        message = WEpochCommit(entry=entry)
        self.broadcast(
            [a for a in self.config.all_acceptors()
             if self._acceptor_ids[a] not in acked], message)
        self.broadcast(
            [lead for lead in self.config.leader_addresses
             if lead != self.address], message)

    def _resend_epoch_commit(self, group: int) -> None:
        record = self._epoch_resends.get(group)
        if record is None:
            return
        self._broadcast_epoch_commit(group)
        record[0].start()

    def _handle_epoch_ack(self, src: Address, m: WEpochAck) -> None:
        record = self._epoch_resends.get(m.group)
        if record is None or record[1].epoch != m.epoch:
            return
        timer, entry, acked = record
        acceptor_id = self._acceptor_ids.get(src)
        if acceptor_id is None:
            return
        acked.add(acceptor_id)
        if self._read_spec.check(acked):
            timer.stop()
            del self._epoch_resends[m.group]

    # --- preemption ---------------------------------------------------------
    def _handle_nack(self, src: Address, m: WNack) -> None:
        self._ballot_floor[m.group] = max(
            self._ballot_floor.get(m.group, -1), m.ballot)
        st = self.stealing.get(m.group)
        if st is not None and m.ballot > st.ballot:
            # Escalate ABOVE the refused ballot -- but after a
            # randomized backoff, never immediately: the competing
            # stealer gets a window to finish, breaking the duel.
            self._phase1_timers[m.group].stop()
            timer = self._steal_retry_timers.get(m.group)
            if timer is None:
                timer = self.timer(
                    f"retrySteal-{m.group}",
                    self.options.steal_backoff_s,
                    lambda g=m.group: self._retry_steal(g))
                self._steal_retry_timers[m.group] = timer
            timer.set_delay(self.options.steal_backoff_s
                            * (0.5 + self._rng.random()))
            timer.reset()
            return
        state = self.active.get(m.group)
        if state is not None and m.ballot > state.ballot:
            self._release_ownership(m.group)

    def _retry_steal(self, group: int) -> None:
        st = self.stealing.get(group)
        if st is None:
            return
        floor = max(self.epochs.max_ballot(group),
                    self._ballot_floor.get(group, -1), st.ballot)
        st.ballot = self.config.next_ballot(self.zone, floor)
        st.epoch = self.epochs.current(group).epoch + 1
        st.phase1bs.clear()
        self._broadcast_phase1a(group)
        self._phase1_timers[group].start()

    def _handle_epoch_commit(self, src: Address, m: WEpochCommit) -> None:
        entry = m.entry
        if self.epochs.offer(entry) in ("new", "replaced"):
            self.trackers[entry.group].note_epochs()
            state = self.active.get(entry.group)
            if state is not None and entry.home_zone != self.zone \
                    and entry.ballot > state.ballot:
                self._release_ownership(entry.group)

    def _release_ownership(self, group: int) -> None:
        state = self.active.pop(group, None)
        if state is None:
            return
        if self._placement is not None:
            self._placement["counts"].pop(group, None)
            self._placement["streak"].pop(group, None)
            self._placement["acquired"].pop(group, None)
        entry = self.epochs.current(group)
        for slot, (value, client, cid) in state.proposals.items():
            if client is not None:
                self.send(client, WNotOwner(
                    group=group, command_id=cid,
                    home_zone=entry.home_zone, ballot=entry.ballot))

    # --- adaptive placement (paxchaos) --------------------------------------
    def _placement_check(self) -> None:
        """One placement-policy evaluation: for every owned group,
        decide whether a remote zone's request-origin EWMA dominates
        enough (for long enough) to hand the group off. The hand-off
        is a Steal trigger to the dominant zone's leader -- the normal
        fresh-ballot steal flow moves the group, this leader gets
        preempted and redirects stragglers via the nack-floor hint
        (the anti-boomerang path PR 13 fixed)."""
        opts = self.options
        state = self._placement
        counts_by_group = state["counts"]
        for group in list(self.active):
            counts = counts_by_group.get(group)
            if not counts:
                continue
            total = sum(counts.values())
            zone = max(counts, key=counts.get)
            share = counts[zone] / total
            streak = state["streak"].setdefault(group, [zone, 0])
            if zone != self.zone and total >= opts.placement_min_samples \
                    and share >= opts.placement_dominance:
                if streak[0] == zone:
                    streak[1] += 1
                else:
                    streak[0], streak[1] = zone, 1
            else:
                streak[0], streak[1] = zone, 0
            dwell = self._clock() - state["acquired"].get(group, 0.0)
            if streak[1] >= opts.placement_hysteresis_checks \
                    and dwell >= opts.placement_min_dwell_s:
                self.send(self.config.leader_addresses[zone],
                          Steal(group=group))
                self.placement_handoffs.append({
                    "group": group, "to_zone": zone,
                    "t_s": round(self._clock(), 3),
                    "share": round(share, 3)})
                counts_by_group.pop(group, None)
                state["streak"].pop(group, None)
                continue
            # EWMA decay: old traffic fades at alpha per check, so
            # dominance tracks the CURRENT origin mix.
            alpha = opts.placement_ewma_alpha
            for origin in list(counts):
                counts[origin] *= (1.0 - alpha)
                if counts[origin] < 0.05:
                    del counts[origin]
        self._placement_timer.start()

    # --- replica hole recovery ----------------------------------------------
    def _handle_recover(self, src: Address, m: WRecover) -> None:
        sent = 0
        for slot in sorted(self.chosen[m.group]):
            if slot < m.slot:
                continue
            self.send(src, WChosen(group=m.group, slot=slot,
                                   value=self.chosen[m.group][slot]))
            sent += 1
            if sent >= self.options.recover_reply_limit:
                break
