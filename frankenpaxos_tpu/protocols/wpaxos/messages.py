"""WPaxos wire messages (paxgeo, docs/GEO.md).

The command space is partitioned by OBJECT into ``num_groups`` object
groups; each group has its own log (slot space), its own leadership
epoch chain (``geo.ObjectEpochStore``), and a home zone whose leader
commits through that zone's ``ZoneGrid`` row -- so steady-state
commits never cross a zone boundary. An object STEAL is an epoch
change driven by a cross-zone Phase1 (WPhase1a/WPhase1b), committed at
a row-majority of WAL-durable old-home promises, and activated with a
watermark-bounded handover (``GeoEpoch.start_slot``).

Ballot space is partitioned by ZONE: zone ``z``'s leader owns ballots
``b`` with ``b % num_zones == z``, so competing stealers can never
collide on a ballot. ``Command``/``CommandId``/value shapes are shared
with multipaxos (one value codec family serves both).

Every message here has a fixed-layout codec from day one (wire.py,
extended tags 160-172) -- paxgeo adds nothing to the COD301 baseline.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from frankenpaxos_tpu.geo.epochs import GeoEpoch
from frankenpaxos_tpu.protocols.multipaxos.messages import (  # noqa: F401
    Command,
    CommandBatch,
    CommandBatchOrNoop,
    CommandId,
    NOOP,
    Noop,
)


@dataclasses.dataclass(frozen=True)
class WRequest:
    """Client write for one object group. ``steal`` marks a failover
    resend: the receiving leader should STEAL the group (cross-zone
    Phase1) instead of redirecting, because the client has given up on
    the home zone answering. ``origin_zone`` is the issuing client's
    zone (-1 = unknown): the feed for the leader's adaptive-placement
    EWMA (paxchaos) -- a routing HINT only, never consulted for
    safety."""

    group: int
    command: Command
    steal: bool = False
    origin_zone: int = -1


@dataclasses.dataclass(frozen=True)
class WReply:
    command_id: CommandId
    group: int
    slot: int
    result: bytes


@dataclasses.dataclass(frozen=True)
class WNotOwner:
    """Routing redirect: the receiver does not own ``group``; retry at
    ``home_zone``'s leader (hint as of ``ballot`` -- clients keep the
    highest-ballot hint)."""

    group: int
    command_id: CommandId
    home_zone: int
    ballot: int


@dataclasses.dataclass(frozen=True)
class Steal:
    """Admin/chaos/placement trigger: steal ``group`` to the receiving
    leader's zone (bench/geo_lt.py's migration arm, the zone-outage
    repair path)."""

    group: int


@dataclasses.dataclass(frozen=True)
class WPhase1a:
    """The steal's cross-zone Phase1: promise ``ballot`` for ``group``
    and report votes + known epochs. ``epoch`` is the epoch id the
    stealer will commit on quorum."""

    group: int
    ballot: int
    epoch: int


@dataclasses.dataclass(frozen=True)
class WVote:
    slot: int
    ballot: int
    value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class WPhase1b:
    """The acceptor's DURABLE steal ack (the WalGeoPromise is fsynced
    before this leaves -- DurableRole): every vote it holds for the
    group plus its known epoch chain, so the stealer adopts in-flight
    values and discovers committed steals it missed (the
    Flexible-Paxos intersection condition over the epoch map)."""

    group: int
    ballot: int
    epoch: int
    acceptor: int
    votes: Tuple[WVote, ...]
    epochs: Tuple[GeoEpoch, ...]


@dataclasses.dataclass(frozen=True)
class WPhase2a:
    group: int
    slot: int
    ballot: int
    value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class WPhase2b:
    group: int
    slot: int
    ballot: int
    acceptor: int


@dataclasses.dataclass(frozen=True)
class WNack:
    """Promise refused: ``ballot`` is the higher promised ballot, and
    ``home_zone`` the refuser's current owner hint for the group."""

    group: int
    ballot: int
    home_zone: int


@dataclasses.dataclass(frozen=True)
class WChosen:
    group: int
    slot: int
    value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class WEpochCommit:
    """The committed steal's epoch entry, broadcast by the new owner
    to acceptors and peer leaders (resent until a read quorum of
    acceptor acks -- discovery is then guaranteed for any future
    Phase1, docs/GEO.md)."""

    entry: GeoEpoch


@dataclasses.dataclass(frozen=True)
class WEpochAck:
    """Durability receipt for one WEpochCommit (the WalGeoEpoch record
    is group-committed before this leaves an acceptor)."""

    group: int
    epoch: int


@dataclasses.dataclass(frozen=True)
class WRecover:
    """Replica hole recovery: send me WChosen for ``group`` slots >=
    ``slot`` (the receiver answers from its chosen log; bounded per
    reply burst)."""

    group: int
    slot: int


#: Handy alias for handlers.
OptionalCommand = Optional[Command]
