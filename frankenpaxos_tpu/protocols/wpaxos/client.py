"""WPaxos Client: object-keyed routing with steal-on-failover.

The client keeps a per-group routing hint (home zone, highest ballot
seen) and sends each write to the hinted zone's leader. Resends ride
an RTT-adaptive timer (``geo.RttEstimator`` -- fixed timeouts
false-positive the moment links have real latency); after
``failover_after`` unanswered resends the client rotates to the next
zone's leader with ``steal=True``, making that leader steal the group
-- the liveness path for a dead home zone. ``WNotOwner`` redirects
(ballot-ordered, so a stale hint never overrides a newer one) repoint
the hint without burning the failover budget.

Latencies are recorded against the transport's VIRTUAL clock when one
exists (GeoSimTransport), so bench/geo_lt.py measures exact simulated
commit latency.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.geo.rtt import RttEstimator
from frankenpaxos_tpu.protocols.wpaxos.config import WPaxosConfig
from frankenpaxos_tpu.protocols.wpaxos.messages import (
    Command,
    CommandId,
    WNotOwner,
    WReply,
    WRequest,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class WPaxosClientOptions:
    resend_period_s: float = 1.0
    #: Resends to one target before rotating zones with steal=True.
    failover_after: int = 2
    #: Adaptive resend deadlines from observed request RTTs.
    adaptive_timeouts: bool = True
    #: paxworld retry discipline (serve/backoff.py): total retries
    #: (timeout resends + Rejected backoffs) per op before the op
    #: concludes with RETRY_EXHAUSTED. 0 = unlimited (the pre-budget
    #: behavior every existing sim/bench keeps). When a budget is
    #: armed, write callbacks must accept the sentinel.
    retry_budget: int = 0
    #: Jittered exponential backoff applied on Rejected (a
    #: serve.backoff.Backoff); None keeps the adaptive resend timer's
    #: own pacing (the pre-paxworld behavior).
    reject_backoff: object = None
    #: This client's zone, stamped on every WRequest as
    #: ``origin_zone`` -- the adaptive-placement EWMA's feed
    #: (paxchaos). -1 (the default) stamps "unknown", which the
    #: placement policy ignores.
    zone: int = -1


@dataclasses.dataclass
class _Pending:
    command_id: CommandId
    group: int
    payload: bytes
    callback: Optional[Callable]
    target_zone: int
    resends: int = 0
    rejects: int = 0
    #: A Rejected arrived and the backoff timer is already rescheduled:
    #: a duplicate Rejected (original + resend both refused) must not
    #: double-consume the retry budget or re-widen the backoff.
    backoff_pending: bool = False
    steal: bool = False
    sent_at: float = 0.0
    first_sent_at: float = 0.0


class WPaxosClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: WPaxosConfig,
                 options: WPaxosClientOptions = WPaxosClientOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.seed = seed
        # pseudonym -> next client_id (sequential per pseudonym).
        self._next_id: dict[int, int] = {}
        #: pseudonym -> in-flight op (one at a time per pseudonym; the
        #: harness's idle_writers contract).
        self.pending: dict[int, _Pending] = {}
        # group -> (home zone hint, ballot the hint is as-of).
        self.routing: dict[int, tuple] = {
            g: (home, home)
            for g, home in enumerate(config.initial_home)}
        self.rtt = RttEstimator()
        self._timers: dict[int, object] = {}
        # Virtual clock when the transport has one (GeoSimTransport:
        # exact simulated latencies); the wall clock otherwise -- a
        # constant would feed 0-RTT samples into the estimator and
        # collapse every resend deadline to its floor (a resend storm
        # on real TCP).
        if hasattr(transport, "now"):
            self._clock = lambda: transport.now
        else:
            import time

            self._clock = time.monotonic
        #: (group, target_zone, latency_s) per completed op -- the
        #: bench's measurement surface.
        self.latencies: list[tuple] = []
        #: RETRY_EXHAUSTED conclusions (the scenario matrix's loud,
        #: bounded degradation path).
        self.giveups = 0
        # String-seeded (sha512, process-stable) -- only the Rejected
        # backoff jitter draws from it, so budget-less clients replay
        # byte-identically to pre-paxworld.
        self._rng = random.Random(f"wpaxos-client|{address}|{seed}")

    # --- the write API ------------------------------------------------------
    def write(self, pseudonym: int, payload: bytes,
              callback: Optional[Callable] = None,
              key: Optional[bytes] = None) -> None:
        if pseudonym in self.pending:
            raise ValueError(f"pseudonym {pseudonym} already has an op")
        group = self.config.group_of_key(key if key is not None
                                         else payload)
        client_id = self._next_id.get(pseudonym, 0)
        self._next_id[pseudonym] = client_id + 1
        cid = CommandId(client_address=self.address,
                        client_pseudonym=pseudonym,
                        client_id=client_id)
        now = self._clock()
        op = _Pending(command_id=cid, group=group, payload=payload,
                      callback=callback,
                      target_zone=self.routing[group][0],
                      sent_at=now, first_sent_at=now)
        self.pending[pseudonym] = op
        self._send(op)
        self._restart_timer(pseudonym)

    def _send(self, op: _Pending) -> None:
        op.sent_at = self._clock()
        self.send(
            self.config.leader_addresses[op.target_zone],
            WRequest(group=op.group,
                     command=Command(command_id=op.command_id,
                                     command=op.payload),
                     steal=op.steal,
                     origin_zone=self.options.zone))

    def _restart_timer(self, pseudonym: int, resends: int = 0) -> None:
        delay = self.options.resend_period_s
        if self.options.adaptive_timeouts:
            delay = max(self.rtt.timeout(delay), 1e-3)
        # Exponential backoff on consecutive unanswered resends: a
        # steal in progress (or a duel resolving) needs WIDENING
        # windows, not a metronome feeding it fresh steal=True
        # requests every tick.
        delay *= min(8.0, 1.5 ** resends)
        timer = self._timers.get(pseudonym)
        if timer is None:
            timer = self.timer(f"resendWrite-{pseudonym}", delay,
                               lambda p=pseudonym: self._resend(p))
            self._timers[pseudonym] = timer
        else:
            timer.stop()
            timer.set_delay(delay)
        timer.start()

    def _resend(self, pseudonym: int) -> None:
        op = self.pending.get(pseudonym)
        if op is None:
            return
        op.backoff_pending = False
        budget = self.options.retry_budget
        if budget and op.resends + op.rejects >= budget:
            self._giveup(pseudonym)
            return
        op.resends += 1
        if op.resends % self.options.failover_after == 0:
            # The hinted zone is not answering: rotate and ask the
            # next zone's leader to steal the object group.
            op.target_zone = (op.target_zone + 1) \
                % self.config.num_zones
            op.steal = True
        self._send(op)
        self._restart_timer(pseudonym, resends=op.resends)

    def _giveup(self, pseudonym: int) -> None:
        """Retry budget exhausted: conclude LOUDLY with the sentinel
        -- never a silent wedge (docs/SERVING.md discipline)."""
        from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED

        op = self.pending.pop(pseudonym)
        timer = self._timers.get(pseudonym)
        if timer is not None:
            timer.stop()
        self.giveups += 1
        if op.callback is not None:
            op.callback(RETRY_EXHAUSTED)

    # --- handlers -----------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, WReply):
            self._handle_reply(src, message)
        elif isinstance(message, WNotOwner):
            self._handle_not_owner(src, message)
        elif type(message).__name__ == "Rejected":
            self._handle_rejected(src, message)
        else:
            self.logger.fatal(f"unexpected client message {message!r}")

    def _handle_reply(self, src: Address, m: WReply) -> None:
        pseudonym = m.command_id.client_pseudonym
        op = self.pending.get(pseudonym)
        if op is None or op.command_id != m.command_id:
            return  # duplicate ack for a completed op
        del self.pending[pseudonym]
        timer = self._timers.get(pseudonym)
        if timer is not None:
            timer.stop()
        now = self._clock()
        self.rtt.observe(now - op.sent_at)
        self.latencies.append((op.group, op.target_zone,
                               now - op.first_sent_at))
        if op.callback is not None:
            op.callback(m.result)

    def _handle_not_owner(self, src: Address, m: WNotOwner) -> None:
        hint_zone, hint_ballot = self.routing.get(
            m.group, (m.home_zone, -1))
        if m.ballot >= hint_ballot:
            self.routing[m.group] = (m.home_zone, m.ballot)
        op = self.pending.get(m.command_id.client_pseudonym)
        if op is None or op.command_id != m.command_id:
            return
        if not op.steal:
            # Follow the redirect immediately (does not burn the
            # failover budget); a steal-mode op stays put -- the
            # stealing leader will answer.
            op.target_zone = self.routing[op.group][0]
            self._send(op)
            self._restart_timer(m.command_id.client_pseudonym)

    def _handle_rejected(self, src: Address, m) -> None:
        """paxload admission refusal: the leader is ALIVE but
        saturated -- back off (jittered exponential when
        ``reject_backoff`` is armed, honoring the server's
        retry_after hint as a floor), consume the retry budget, and
        retry the SAME leader; never treat it as a death signal (no
        steal, no failover rotation).

        (Known accepted duplication: this budget/backoff_pending/
        RETRY_EXHAUSTED state machine mirrors protocols/craq.py and
        the multipaxos/mencius retry discipline, pending the
        protocol-neutral client-layer refactor on the ROADMAP --
        change one, check the others.)"""
        for pseudonym, client_id in m.entries:
            op = self.pending.get(pseudonym)
            if op is None or op.command_id.client_id != client_id:
                continue
            op.steal = False
            if op.backoff_pending:
                continue  # duplicate refusal of one attempt
            op.rejects += 1
            budget = self.options.retry_budget
            if budget and op.resends + op.rejects >= budget:
                self._giveup(pseudonym)
                continue
            # Set UNCONDITIONALLY (cleared when the resend timer
            # fires): with no backoff armed, a duplicate refusal of
            # one attempt (original + resend both refused) must still
            # not double-consume the budget.
            op.backoff_pending = True
            backoff = self.options.reject_backoff
            if backoff is None:
                continue  # the running resend timer paces the retry
            delay = backoff.delay_s(
                op.rejects - 1, self._rng,
                floor_s=getattr(m, "retry_after_ms", 0) / 1000.0)
            timer = self._timers.get(pseudonym)
            if timer is not None:
                timer.stop()
                timer.set_delay(delay)
                timer.start()
