"""WPaxos Acceptor: one grid cell, serving every object group.

Per-group state only -- a promised ballot, per-slot votes, and the
group's known epoch chain. The acceptor never evaluates quorums; it
enforces the two Paxos vote rules (promise monotonicity, vote-at-
promised-ballot) per group and reports durable state to stealers.

Durability follows the paxlog group-commit discipline (wal/role.py):
promises, votes, and epoch entries append to the WAL as they are
handled, and every ack that depends on one (WPhase1b, WPhase2b,
WEpochAck) is held in ``_wal_sends`` until ``on_drain``'s single fsync
releases it. That ordering is what makes a row-majority of WPhase1b
acks a real steal commit: a crashed old-home acceptor can never have
acked a promise it will not recover.
"""

from __future__ import annotations

from frankenpaxos_tpu.geo.epochs import GeoEpoch, ObjectEpochStore
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    decode_value,
    encode_value,
)
from frankenpaxos_tpu.protocols.wpaxos.config import WPaxosConfig
from frankenpaxos_tpu.protocols.wpaxos.messages import (
    WEpochAck,
    WEpochCommit,
    WNack,
    WPhase1a,
    WPhase1b,
    WPhase2a,
    WPhase2b,
    WVote,
)
from frankenpaxos_tpu.protocols.wpaxos.wire import (
    decode_geo_epoch,
    encode_geo_epoch,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.wal import (
    DurableRole,
    WalGeoEpoch,
    WalGeoPromise,
    WalGeoVote,
    WalSnapshot,
)


class WPaxosAcceptor(Actor, DurableRole):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: WPaxosConfig, wal=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.zone = next(
            z for z, row in enumerate(config.acceptor_addresses)
            if address in row)
        self.index = config.acceptor_addresses[self.zone].index(address)
        self.acceptor_id = config.acceptor_id(self.zone, self.index)
        # Per-group promised ballot (-1: anything goes).
        self.promised: dict[int, int] = {}
        # Per-group votes: group -> {slot: (ballot, value)}.
        self.votes: dict[int, dict] = {}
        self.epochs = ObjectEpochStore(config.num_groups,
                                       config.initial_home)
        self._wal_init(wal)
        if wal is not None:
            self._recover_from_wal()

    # --- durability ---------------------------------------------------------
    def _recover_from_wal(self) -> None:
        for record in self.wal.recover(self.logger):
            if isinstance(record, WalSnapshot):
                self.promised.clear()
                self.votes.clear()
                self.epochs = ObjectEpochStore(
                    self.config.num_groups, self.config.initial_home)
            elif isinstance(record, WalGeoPromise):
                self.promised[record.group] = max(
                    self.promised.get(record.group, -1), record.ballot)
            elif isinstance(record, WalGeoVote):
                self.promised[record.group] = max(
                    self.promised.get(record.group, -1), record.ballot)
                self.votes.setdefault(record.group, {})[record.slot] = (
                    record.ballot, decode_value(record.value))
            elif isinstance(record, WalGeoEpoch):
                self.epochs.offer(decode_geo_epoch(record.payload))
            else:
                self.logger.fatal(
                    f"unexpected wpaxos acceptor WAL record {record!r}")

    def _wal_compact(self) -> None:
        records: list = []
        for group in sorted(self.promised):
            records.append(WalGeoPromise(group=group,
                                         ballot=self.promised[group]))
        for group in range(self.config.num_groups):
            for entry in self.epochs.known(group):
                if entry.epoch > 0:
                    records.append(WalGeoEpoch(
                        payload=encode_geo_epoch(entry)))
        for group in sorted(self.votes):
            for slot in sorted(self.votes[group]):
                ballot, value = self.votes[group][slot]
                records.append(WalGeoVote(
                    group=group, slot=slot, ballot=ballot,
                    value=encode_value(value)))
        self.wal.compact(WalSnapshot(payload=b""), records)

    # --- handlers -----------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, WPhase2a):
            self._handle_phase2a(src, message)
        elif isinstance(message, WPhase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, WEpochCommit):
            self._handle_epoch_commit(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_phase1a(self, src: Address, m: WPhase1a) -> None:
        promised = self.promised.get(m.group, -1)
        if m.ballot <= promised:
            self.send(src, WNack(
                group=m.group, ballot=promised,
                home_zone=self.epochs.current(m.group).home_zone))
            return
        self.promised[m.group] = m.ballot
        if self.wal is not None:
            self.wal.append(WalGeoPromise(group=m.group,
                                          ballot=m.ballot))
        votes = tuple(
            WVote(slot=slot, ballot=ballot, value=value)
            for slot, (ballot, value)
            in sorted(self.votes.get(m.group, {}).items()))
        # The durable steal ack: released only after the promise's
        # group-commit fsync (DurableRole).
        self._wal_send(src, WPhase1b(
            group=m.group, ballot=m.ballot, epoch=m.epoch,
            acceptor=self.acceptor_id, votes=votes,
            epochs=self.epochs.known(m.group)))

    def _handle_phase2a(self, src: Address, m: WPhase2a) -> None:
        promised = self.promised.get(m.group, -1)
        if m.ballot < promised:
            self.send(src, WNack(
                group=m.group, ballot=promised,
                home_zone=self.epochs.current(m.group).home_zone))
            return
        existing = self.votes.get(m.group, {}).get(m.slot)
        if existing is not None and existing[0] > m.ballot:
            return  # stale duplicate below an already-voted ballot
        if existing is not None and existing[0] == m.ballot \
                and existing[1] != m.value:
            # Votes are WRITE-ONCE per (slot, ballot): one ballot has
            # one proposer, so a conflicting twin is a protocol-error
            # frame (or an amnesiac proposer) -- re-acking it would
            # let a second value ride the first value's quorum.
            return
        if m.ballot > promised:
            # Voting at b implicitly promises b.
            self.promised[m.group] = m.ballot
            if self.wal is not None:
                self.wal.append(WalGeoPromise(group=m.group,
                                              ballot=m.ballot))
        if existing is None or existing[0] != m.ballot:
            self.votes.setdefault(m.group, {})[m.slot] = (m.ballot,
                                                          m.value)
            if self.wal is not None:
                self.wal.append(WalGeoVote(
                    group=m.group, slot=m.slot, ballot=m.ballot,
                    value=encode_value(m.value)))
        self._wal_send(src, WPhase2b(group=m.group, slot=m.slot,
                                     ballot=m.ballot,
                                     acceptor=self.acceptor_id))

    def _handle_epoch_commit(self, src: Address, m: WEpochCommit) -> None:
        entry: GeoEpoch = m.entry
        verdict = self.epochs.offer(entry)
        if verdict in ("new", "replaced"):
            if self.wal is not None:
                self.wal.append(WalGeoEpoch(
                    payload=encode_geo_epoch(entry)))
            self._wal_send(src, WEpochAck(group=entry.group,
                                          epoch=entry.epoch))
        elif verdict == "dup":
            # Already durable from the drain that first logged it; the
            # re-ack still rides the group-commit release path so the
            # ordering invariant holds uniformly (DUR501).
            self._wal_send(src, WEpochAck(group=entry.group,
                                          epoch=entry.epoch))

    def on_drain(self) -> None:
        self._wal_drain()
