"""WPaxos Replica: one per zone, executing every object group.

Replicas are the exactly-once authority: each group's log executes in
slot order, with a per-(group, client, pseudonym) client table
filtering duplicate commands -- a command that reached two slots (a
client failover re-propose racing a steal's adopted vote) executes
once, whichever slot wins. The leader already acked the client at
chosen-time (zone-local); replicas exist for execution, reads, and the
chaos oracle (prefix agreement + exactly-once across replicas,
tests/protocols/test_wpaxos.py).

Holes (a dropped WChosen) recover via a ``recover`` timer: ask every
leader for chosen values at or above the executed watermark -- any
leader that remembers the slot answers, including a steal's new owner
which re-proved the value from acceptor votes.
"""

from __future__ import annotations

from frankenpaxos_tpu.protocols.wpaxos.config import WPaxosConfig
from frankenpaxos_tpu.protocols.wpaxos.messages import (
    CommandBatch,
    WChosen,
    WRecover,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


class WPaxosReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: WPaxosConfig,
                 recover_period_s: float = 1.0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.zone = config.replica_addresses.index(address)
        # Per group: chosen log, contiguous executed watermark, the
        # executed payload sequence (the AppendLog-flavored SM), and
        # the max slot we have HEARD of (hole detection).
        self.logs: list[dict] = [dict() for _ in range(config.num_groups)]
        self.executed_watermark: list[int] = [0] * config.num_groups
        self.executed: list[list] = [[] for _ in range(config.num_groups)]
        self.max_known_slot: list[int] = [-1] * config.num_groups
        # (group, client, pseudonym) -> highest executed client_id.
        self.client_table: dict = {}
        self.recover_timer = self.timer("recover", recover_period_s,
                                        self._recover)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, WChosen):
            self._handle_chosen(src, message)
        else:
            self.logger.fatal(f"unexpected replica message {message!r}")

    def _handle_chosen(self, src: Address, m: WChosen) -> None:
        if not 0 <= m.group < self.config.num_groups:
            return
        log = self.logs[m.group]
        if m.slot not in log:
            log[m.slot] = m.value
        self.max_known_slot[m.group] = max(self.max_known_slot[m.group],
                                           m.slot)
        self._execute(m.group)
        if self.max_known_slot[m.group] >= \
                self.executed_watermark[m.group] \
                and not self.recover_timer.running:
            self.recover_timer.start()

    def _execute(self, group: int) -> None:
        log = self.logs[group]
        wm = self.executed_watermark[group]
        while wm in log:
            value = log[wm]
            if isinstance(value, CommandBatch):
                for command in value.commands:
                    cid = command.command_id
                    key = (group, cid.client_address,
                           cid.client_pseudonym)
                    if cid.client_id > self.client_table.get(key, -1):
                        self.client_table[key] = cid.client_id
                        self.executed[group].append(command.command)
            wm += 1
        self.executed_watermark[group] = wm

    def _recover(self) -> None:
        """Ask every leader to refill holes in any lagging group."""
        lagging = False
        for group in range(self.config.num_groups):
            if self.max_known_slot[group] >= \
                    self.executed_watermark[group]:
                lagging = True
                self.broadcast(
                    self.config.leader_addresses,
                    WRecover(group=group,
                             slot=self.executed_watermark[group]))
        if lagging:
            self.recover_timer.start()

    # --- oracle views (tests) ----------------------------------------------
    def group_sequences(self) -> tuple:
        return tuple(tuple(seq) for seq in self.executed)
