"""Fixed-layout codecs for every WPaxos message (extended tags 160-172).

paxgeo messages get codecs from DAY ONE -- the unit adds nothing to
the COD301 baseline, every frame is lane-classifiable by its leading
tag (serve/lanes.py: WRequest is client lane), and the registry-wide
corrupt-frame fuzz (tests/test_wire_codecs.py) holds each decode to
the ValueError containment contract.

Address/command/value layouts are shared with multipaxos (one value
codec family serves both protocols), and ``encode_geo_epoch`` /
``decode_geo_epoch`` double as the WAL payload codec for
``wal.records.WalGeoEpoch`` -- one layout for the wire and the log.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.geo.epochs import GeoEpoch
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_cid,
    _put_command,
    _put_value,
    _take_cid,
    _take_command,
    _take_value,
)
from frankenpaxos_tpu.protocols.wpaxos.messages import (
    Steal,
    WChosen,
    WEpochAck,
    WEpochCommit,
    WNack,
    WNotOwner,
    WPhase1a,
    WPhase1b,
    WPhase2a,
    WPhase2b,
    WRecover,
    WReply,
    WRequest,
    WVote,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_QQ = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")
_QQQQ = struct.Struct("<qqqq")
_GEO_EPOCH = struct.Struct("<qqqqq")  # group, epoch, start, home, ballot

#: Hostile-count bound: no real Phase1b carries more votes/epochs than
#: this; a corrupt length field must not size an allocation.
_MAX_ITEMS = 1 << 20


def encode_geo_epoch(entry: GeoEpoch) -> bytes:
    """One GeoEpoch as a standalone byte segment (the WalGeoEpoch
    payload; the same layout WEpochCommit carries on the wire)."""
    return _GEO_EPOCH.pack(entry.group, entry.epoch, entry.start_slot,
                           entry.home_zone, entry.ballot)


def decode_geo_epoch(data: bytes) -> GeoEpoch:
    try:
        group, epoch, start, home, ballot = _GEO_EPOCH.unpack_from(
            data, 0)
    except struct.error as e:
        raise ValueError(f"corrupt geo epoch: {e!r}") from e
    return GeoEpoch(group=group, epoch=epoch, start_slot=start,
                    home_zone=home, ballot=ballot)


def _put_geo_epoch(out: bytearray, entry: GeoEpoch) -> None:
    out += encode_geo_epoch(entry)


def _take_geo_epoch(buf: bytes, at: int):
    group, epoch, start, home, ballot = _GEO_EPOCH.unpack_from(buf, at)
    return GeoEpoch(group=group, epoch=epoch, start_slot=start,
                    home_zone=home, ballot=ballot), at + _GEO_EPOCH.size


def _take_count(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    if not 0 <= n <= _MAX_ITEMS:
        raise ValueError(f"malformed item count {n}")
    return n, at + 4


class WRequestCodec(MessageCodec):
    message_type = WRequest
    tag = 160

    def encode(self, out, message):
        out += _I64.pack(message.group)
        out.append(1 if message.steal else 0)
        # origin_zone as one signed byte (-1 = unknown; no topology
        # runs 127+ zones through a single client).
        out.append(message.origin_zone & 0xFF)
        _put_command(out, message.command)

    def decode(self, buf, at):
        (group,) = _I64.unpack_from(buf, at)
        steal = buf[at + 8] != 0
        origin = buf[at + 9]
        if origin > 127:
            origin -= 256
        command, at = _take_command(buf, at + 10)
        return WRequest(group=group, command=command, steal=steal,
                        origin_zone=origin), at


class WReplyCodec(MessageCodec):
    message_type = WReply
    tag = 161

    def encode(self, out, message):
        out += _QQ.pack(message.group, message.slot)
        _put_cid(out, message.command_id)
        out += _I32.pack(len(message.result))
        out += message.result

    def decode(self, buf, at):
        group, slot = _QQ.unpack_from(buf, at)
        cid, at = _take_cid(buf, at + 16)
        n, at = _take_count(buf, at)
        if at + n > len(buf):
            raise ValueError(f"result overruns frame ({n} bytes)")
        result = bytes(buf[at:at + n])
        return WReply(command_id=cid, group=group, slot=slot,
                      result=result), at + n


class WNotOwnerCodec(MessageCodec):
    message_type = WNotOwner
    tag = 162

    def encode(self, out, message):
        out += _QQQ.pack(message.group, message.home_zone,
                         message.ballot)
        _put_cid(out, message.command_id)

    def decode(self, buf, at):
        group, home, ballot = _QQQ.unpack_from(buf, at)
        cid, at = _take_cid(buf, at + 24)
        return WNotOwner(group=group, command_id=cid, home_zone=home,
                         ballot=ballot), at


class StealCodec(MessageCodec):
    message_type = Steal
    tag = 163

    def encode(self, out, message):
        out += _I64.pack(message.group)

    def decode(self, buf, at):
        (group,) = _I64.unpack_from(buf, at)
        return Steal(group=group), at + 8


class WPhase1aCodec(MessageCodec):
    message_type = WPhase1a
    tag = 164

    def encode(self, out, message):
        out += _QQQ.pack(message.group, message.ballot, message.epoch)

    def decode(self, buf, at):
        group, ballot, epoch = _QQQ.unpack_from(buf, at)
        return WPhase1a(group=group, ballot=ballot, epoch=epoch), at + 24


class WPhase1bCodec(MessageCodec):
    message_type = WPhase1b
    tag = 165

    def encode(self, out, message):
        out += _QQQQ.pack(message.group, message.ballot, message.epoch,
                          message.acceptor)
        out += _I32.pack(len(message.votes))
        for vote in message.votes:
            out += _QQ.pack(vote.slot, vote.ballot)
            _put_value(out, vote.value)
        out += _I32.pack(len(message.epochs))
        for entry in message.epochs:
            _put_geo_epoch(out, entry)

    def decode(self, buf, at):
        group, ballot, epoch, acceptor = _QQQQ.unpack_from(buf, at)
        at += 32
        n, at = _take_count(buf, at)
        votes = []
        for _ in range(n):
            slot, vote_ballot = _QQ.unpack_from(buf, at)
            value, at = _take_value(buf, at + 16)
            votes.append(WVote(slot=slot, ballot=vote_ballot,
                               value=value))
        n, at = _take_count(buf, at)
        epochs = []
        for _ in range(n):
            entry, at = _take_geo_epoch(buf, at)
            epochs.append(entry)
        return WPhase1b(group=group, ballot=ballot, epoch=epoch,
                        acceptor=acceptor, votes=tuple(votes),
                        epochs=tuple(epochs)), at


class WPhase2aCodec(MessageCodec):
    message_type = WPhase2a
    tag = 166

    def encode(self, out, message):
        out += _QQQ.pack(message.group, message.slot, message.ballot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        group, slot, ballot = _QQQ.unpack_from(buf, at)
        value, at = _take_value(buf, at + 24)
        return WPhase2a(group=group, slot=slot, ballot=ballot,
                        value=value), at


class WPhase2bCodec(MessageCodec):
    message_type = WPhase2b
    tag = 167

    def encode(self, out, message):
        out += _QQQQ.pack(message.group, message.slot, message.ballot,
                          message.acceptor)

    def decode(self, buf, at):
        group, slot, ballot, acceptor = _QQQQ.unpack_from(buf, at)
        return WPhase2b(group=group, slot=slot, ballot=ballot,
                        acceptor=acceptor), at + 32


class WNackCodec(MessageCodec):
    message_type = WNack
    tag = 168

    def encode(self, out, message):
        out += _QQQ.pack(message.group, message.ballot,
                         message.home_zone)

    def decode(self, buf, at):
        group, ballot, home = _QQQ.unpack_from(buf, at)
        return WNack(group=group, ballot=ballot, home_zone=home), at + 24


class WChosenCodec(MessageCodec):
    message_type = WChosen
    tag = 169

    def encode(self, out, message):
        out += _QQ.pack(message.group, message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        group, slot = _QQ.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return WChosen(group=group, slot=slot, value=value), at


class WEpochCommitCodec(MessageCodec):
    message_type = WEpochCommit
    tag = 170

    def encode(self, out, message):
        _put_geo_epoch(out, message.entry)

    def decode(self, buf, at):
        entry, at = _take_geo_epoch(buf, at)
        return WEpochCommit(entry=entry), at


class WEpochAckCodec(MessageCodec):
    message_type = WEpochAck
    tag = 171

    def encode(self, out, message):
        out += _QQ.pack(message.group, message.epoch)

    def decode(self, buf, at):
        group, epoch = _QQ.unpack_from(buf, at)
        return WEpochAck(group=group, epoch=epoch), at + 16


class WRecoverCodec(MessageCodec):
    message_type = WRecover
    tag = 172

    def encode(self, out, message):
        out += _QQ.pack(message.group, message.slot)

    def decode(self, buf, at):
        group, slot = _QQ.unpack_from(buf, at)
        return WRecover(group=group, slot=slot), at + 16


for _codec in (WRequestCodec(), WReplyCodec(), WNotOwnerCodec(),
               StealCodec(), WPhase1aCodec(), WPhase1bCodec(),
               WPhase2aCodec(), WPhase2bCodec(), WNackCodec(),
               WChosenCodec(), WEpochCommitCodec(), WEpochAckCodec(),
               WRecoverCodec()):
    register_codec(_codec)
