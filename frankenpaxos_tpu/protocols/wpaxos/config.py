"""WPaxos deployment configuration.

Zones are the unit of placement: one leader, one replica, and one
acceptor ROW (the ``ZoneGrid`` row, width ``2 * f_n + 1``) per zone.
Acceptors carry GLOBAL integer ids ``zone * row_width + i`` -- the
fixed universe every ``QuorumSpec`` (and the fused checkers) index,
stable across steals because steals move leadership, not membership.
"""

from __future__ import annotations

import dataclasses
import zlib

from frankenpaxos_tpu.quorums import ZoneGrid


@dataclasses.dataclass(frozen=True)
class WPaxosConfig:
    zones: tuple                 # zone names, index = zone id
    leader_addresses: tuple      # [zone]
    acceptor_addresses: tuple    # [zone][i], equal-width rows
    replica_addresses: tuple     # [zone]
    num_groups: int = 4
    initial_home: tuple = ()     # group -> zone id; () = round-robin

    def __post_init__(self):
        object.__setattr__(self, "zones", tuple(self.zones))
        object.__setattr__(self, "leader_addresses",
                           tuple(self.leader_addresses))
        object.__setattr__(
            self, "acceptor_addresses",
            tuple(tuple(row) for row in self.acceptor_addresses))
        object.__setattr__(self, "replica_addresses",
                           tuple(self.replica_addresses))
        if not self.initial_home:
            object.__setattr__(
                self, "initial_home",
                tuple(g % len(self.zones)
                      for g in range(self.num_groups)))
        else:
            object.__setattr__(self, "initial_home",
                               tuple(self.initial_home))

    def check_valid(self) -> None:
        z = len(self.zones)
        if z < 1:
            raise ValueError("need at least one zone")
        if len(self.leader_addresses) != z:
            raise ValueError("need exactly one leader per zone")
        if len(self.replica_addresses) != z:
            raise ValueError("need exactly one replica per zone")
        if len(self.acceptor_addresses) != z:
            raise ValueError("need exactly one acceptor row per zone")
        width = len(self.acceptor_addresses[0])
        if width < 1 or any(len(row) != width
                            for row in self.acceptor_addresses):
            raise ValueError("acceptor rows must be equal-width >= 1")
        if self.num_groups < 1:
            raise ValueError("need at least one object group")
        if len(self.initial_home) != self.num_groups:
            raise ValueError(
                f"{len(self.initial_home)} initial homes != "
                f"{self.num_groups} groups")
        if any(not 0 <= h < z for h in self.initial_home):
            raise ValueError(f"initial home outside 0..{z - 1}")

    # --- derived views -----------------------------------------------------
    @property
    def num_zones(self) -> int:
        return len(self.zones)

    @property
    def row_width(self) -> int:
        return len(self.acceptor_addresses[0])

    def grid(self) -> ZoneGrid:
        """The quorum geometry over GLOBAL acceptor ids: rows are
        zones; Phase2 = home-row majority, Phase1 = every row's
        majority (quorums.ZoneGrid)."""
        width = self.row_width
        return ZoneGrid([[zone * width + i for i in range(width)]
                         for zone in range(self.num_zones)])

    def acceptor_id(self, zone: int, index: int) -> int:
        return zone * self.row_width + index

    def acceptor_address(self, acceptor_id: int):
        zone, index = divmod(acceptor_id, self.row_width)
        return self.acceptor_addresses[zone][index]

    def all_acceptors(self) -> tuple:
        return tuple(a for row in self.acceptor_addresses for a in row)

    def row_addresses(self, zone: int) -> tuple:
        return tuple(self.acceptor_addresses[zone])

    def group_of_key(self, key: bytes) -> int:
        """Object -> group routing: crc32 is stable across processes
        and platforms (unlike ``hash`` under PYTHONHASHSEED)."""
        return zlib.crc32(key) % self.num_groups

    # --- ballots ------------------------------------------------------------
    def ballot_zone(self, ballot: int) -> int:
        """Ballot space is partitioned by zone: ballot b belongs to
        zone ``b % num_zones``'s leader."""
        return ballot % self.num_zones

    def next_ballot(self, zone: int, above: int) -> int:
        """Zone ``zone``'s smallest owned ballot strictly greater than
        ``above``."""
        z = self.num_zones
        k = max(0, (above - zone) // z + 1)
        ballot = k * z + zone
        while ballot <= above:
            ballot += z
        return ballot
