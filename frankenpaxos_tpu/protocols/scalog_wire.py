"""Binary codecs for the Scalog hot path.

Scalog's steady state (scalog/Scalog.proto): clients write to shard
servers (ClientRequest/Backup), servers gossip watermark vectors
(ShardInfo), the aggregator proposes cuts, and replicas execute Chosen
batches and reply. Watermark vectors pack as ``[i32 n][n x i64]``.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import scalog as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_I32I64 = struct.Struct("<iq")


def _put_command(out: bytearray, command: m.Command) -> None:
    _put_address(out, command.command_id.client_address)
    out += _I64.pack(command.command_id.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    (client_id,) = _I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 8)
    return m.Command(m.CommandId(address, client_id), payload), at


def _put_watermark(out: bytearray, watermark: tuple) -> None:
    out += _I32.pack(len(watermark))
    for value in watermark:
        out += _I64.pack(value)


def _take_watermark(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    values = []
    for _ in range(n):
        (v,) = _I64.unpack_from(buf, at)
        values.append(v)
        at += 8
    return tuple(values), at


class SClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 37

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class BackupCodec(MessageCodec):
    message_type = m.Backup
    tag = 38

    def encode(self, out, message):
        out += _I32I64.pack(message.server_index, message.slot)
        _put_command(out, message.command)

    def decode(self, buf, at):
        server, slot = _I32I64.unpack_from(buf, at)
        command, at = _take_command(buf, at + _I32I64.size)
        return m.Backup(server, slot, command), at


class ShardInfoCodec(MessageCodec):
    message_type = m.ShardInfo
    tag = 39

    def encode(self, out, message):
        out += _I32.pack(message.shard_index)
        out += _I32.pack(message.server_index)
        _put_watermark(out, message.watermark)

    def decode(self, buf, at):
        (shard,) = _I32.unpack_from(buf, at)
        (server,) = _I32.unpack_from(buf, at + 4)
        watermark, at = _take_watermark(buf, at + 8)
        return m.ShardInfo(shard, server, watermark), at


class CutChosenCodec(MessageCodec):
    message_type = m.CutChosen
    tag = 40

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_watermark(out, message.cut.watermark)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        watermark, at = _take_watermark(buf, at + 8)
        return m.CutChosen(slot, m.GlobalCut(watermark)), at


class SChosenCodec(MessageCodec):
    message_type = m.Chosen
    tag = 41

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        out += _I32.pack(len(message.commands))
        for command in message.commands:
            _put_command(out, command)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        (n,) = _I32.unpack_from(buf, at + 8)
        at += 12
        commands = []
        for _ in range(n):
            command, at = _take_command(buf, at)
            commands.append(command)
        return m.Chosen(slot, tuple(commands)), at


class SClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 42

    def encode(self, out, message):
        _put_address(out, message.command_id.client_address)
        out += _I64I64.pack(message.command_id.client_id, message.slot)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        client_id, slot = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, client_id), slot,
                             result), at


class ProposeCutCodec(MessageCodec):
    """The aggregator -> leader cut proposal (extended tag 190; paxsafe
    COD301 burn-down -- steady-state per-proposal traffic that was
    riding pickle)."""

    message_type = m.ProposeCut
    tag = 190

    def encode(self, out, message):
        _put_watermark(out, message.cut.watermark)

    def decode(self, buf, at):
        watermark, at = _take_watermark(buf, at)
        return m.ProposeCut(m.GlobalCut(watermark)), at


class RawCutChosenCodec(MessageCodec):
    """Leader -> aggregator chosen raw cut (extended tag 191): a
    GlobalCut-or-Noop behind a one-byte flag."""

    message_type = m.RawCutChosen
    tag = 191

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        if isinstance(message.raw_cut_or_noop, m.Noop):
            out.append(0)
        else:
            out.append(1)
            _put_watermark(out, message.raw_cut_or_noop.watermark)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        kind = buf[at + 8]
        at += 9
        if kind == 0:
            return m.RawCutChosen(slot, m.Noop()), at
        if kind != 1:
            raise ValueError(f"bad RawCutChosen flag {kind}")
        watermark, at = _take_watermark(buf, at)
        return m.RawCutChosen(slot, m.GlobalCut(watermark)), at


for _codec in (SClientRequestCodec(), BackupCodec(), ShardInfoCodec(),
               CutChosenCodec(), SChosenCodec(), SClientReplyCodec(),
               ProposeCutCodec(), RawCutChosenCodec()):
    register_codec(_codec)
