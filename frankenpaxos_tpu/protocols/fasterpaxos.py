"""Faster Paxos: delegate-based multi-leader MultiPaxos.

Reference behavior: fasterpaxos/ (FasterPaxos.proto:1-130 protocol
cheatsheet, Server.scala ~1,900 LoC, Client.scala). 2f+1 servers; in
each round one server is the *leader* and picks f+1 *delegates*
(including itself). The leader runs Phase1 across the servers, repairs
the log, then hands the suffix to the delegates (Phase2aAny). In normal
operation clients send to any delegate, which assigns one of its
round-robin-owned slots, noop-fills the unfilled slots just before it
(Server.scala:808-855), votes, and gathers Phase2bs from the other
delegates -- all f+1 delegates voting forms a classic quorum -- then
broadcasts Phase3a (chosen) to all servers and answers the client.
Stale clients discover the round/delegates via RoundInfo.

Options (Server.scala:35-90):
  * ``ack_noops_with_commands``: a delegate that receives a noop
    Phase2a for a slot where it already voted a command replies with a
    Phase2b carrying the command; the noop's proposer throws away its
    noop votes and starts counting command votes
    (Server.scala:1016-1110).
  * ``use_f1_optimization``: with f=1 there are exactly two delegates,
    so a delegate that votes for the other's Phase2a knows the value is
    chosen immediately (Server.scala:1562-1600).
  * heartbeat-driven round change: each server watches the delegates
    via a heartbeat participant and starts Phase1 in its own next round
    when one looks dead (Server.scala:500-527).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

from frankenpaxos_tpu.heartbeat import HeartbeatParticipant
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap


@dataclasses.dataclass(frozen=True)
class FasterPaxosConfig:
    f: int
    server_addresses: tuple

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.server_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 servers")


@dataclasses.dataclass(frozen=True)
class FasterPaxosOptions:
    """Server options (ServerOptions, Server.scala:35-90)."""

    ack_noops_with_commands: bool = True
    use_f1_optimization: bool = True
    # How often each server checks the delegates for liveness (the
    # reference picks uniformly in [min, max]).
    leader_change_min_period_s: float = 5.0
    leader_change_max_period_s: float = 10.0


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
CommandOrNoop = Union[Command, Noop]


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    round: int
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    result: bytes


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    chosen_watermark: int


@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    vote_round: int
    vote_value: CommandOrNoop
    chosen: bool


@dataclasses.dataclass(frozen=True)
class Phase1b:
    server_index: int
    round: int
    info: tuple[Phase1bSlotInfo, ...]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    value: CommandOrNoop


@dataclasses.dataclass(frozen=True)
class Phase2b:
    server_index: int
    slot: int
    round: int
    # ack_noops_with_commands: set when acking a noop Phase2a with the
    # command we already voted for (Server.scala:1613-1625).
    command: Optional[Command] = None


@dataclasses.dataclass(frozen=True)
class Phase3a:
    slot: int
    value: CommandOrNoop


@dataclasses.dataclass(frozen=True)
class Phase2aAny:
    round: int
    delegates: tuple[int, ...]
    start_slot: int


@dataclasses.dataclass(frozen=True)
class Phase2aAnyAck:
    server_index: int
    round: int


@dataclasses.dataclass(frozen=True)
class RoundInfo:
    round: int
    delegates: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Nack:
    round: int


@dataclasses.dataclass
class _LogEntry:
    vote_round: int
    vote_value: CommandOrNoop
    chosen: bool = False


class FasterPaxosServer(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FasterPaxosConfig,
                 state_machine: StateMachine,
                 options: FasterPaxosOptions = FasterPaxosOptions(),
                 heartbeat: Optional[HeartbeatParticipant] = None,
                 heartbeat_addresses: tuple = (), seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = list(config.server_addresses).index(address)
        self.round_system = ClassicRoundRobin(len(config.server_addresses))
        self.round = 0
        # Round 0: server 0 leads with delegates 0..f.
        self.delegates: tuple[int, ...] = tuple(range(config.f + 1))
        self.log: BufferMap = BufferMap()
        self.executed_watermark = 0
        self.client_table: dict[tuple, tuple[int, bytes]] = {}
        # Delegate state: our next owned slot and pending vote collection.
        self.delegate_start = 0
        self.next_owned_slot: Optional[int] = None
        self.pending_votes: dict[int, set[int]] = {}  # slot -> voters
        self.pending_values: dict[int, CommandOrNoop] = {}
        # Leader round-change state.
        self.phase1bs: dict[int, Phase1b] = {}
        self.in_phase1 = False
        if self.index in self.delegates:
            self._set_delegate_slots(0)
        # Heartbeat-driven leader change (Server.scala:500-527): watch
        # the delegates; take over when one looks dead.
        self.heartbeat = heartbeat
        self.heartbeat_addresses = tuple(heartbeat_addresses)
        if heartbeat is not None:
            if len(self.heartbeat_addresses) \
                    != len(config.server_addresses):
                raise ValueError(
                    "heartbeat_addresses must mirror server_addresses")

            def leader_change():
                self._maybe_change_leader()
                self.leader_change_timer.start()

            self.leader_change_timer = self.timer(
                "leaderChange",
                self.rng.uniform(options.leader_change_min_period_s,
                                 options.leader_change_max_period_s),
                leader_change)
            self.leader_change_timer.start()

    # --- helpers ----------------------------------------------------------
    @property
    def is_delegate(self) -> bool:
        return self.index in self.delegates

    def _advance_round(self, new_round: int) -> None:
        """Adopt ``new_round`` and leave any old-round delegate role.

        A server whose round is advanced by another leader's Phase1a or
        by a new delegate's Phase2a is, at that point, NOT a delegate of
        the new round (only Phase2aAny grants that). Keeping the stale
        ``delegates`` set would let it keep assigning its old owned
        slots and proposing fresh commands in the new round -- two
        different commands could then be chosen for one slot (found by
        randomized simulation under round churn; the reference
        transitions Delegate -> Idle on these messages,
        Server.scala:941-999).
        """
        if new_round <= self.round:
            return
        self.round = new_round
        self.delegates = ()
        self.in_phase1 = False
        self.pending_votes.clear()
        self.pending_values.clear()

    @property
    def is_leader(self) -> bool:
        return self.round_system.leader(self.round) == self.index

    def _set_delegate_slots(self, start_slot: int) -> None:
        """Delegate i of the round owns slots start + i, start + i + (f+1),
        ... (the Mencius-style stripe among delegates)."""
        position = self.delegates.index(self.index)
        self.delegate_start = start_slot
        self.next_owned_slot = start_slot + position
        self._skip_filled_slots()

    def _advance_owned_slot(self) -> None:
        if not self.is_delegate:  # delegates=() after _advance_round
            return
        self.next_owned_slot += len(self.delegates)
        self._skip_filled_slots()

    def _skip_filled_slots(self) -> None:
        # getNextSlot (Server.scala:608-630): skip owned slots that were
        # already filled (e.g. noop-filled by a faster delegate).
        while self.log.get(self.next_owned_slot) is not None:
            self.next_owned_slot += len(self.delegates)

    def _owns_slot(self, slot: int) -> bool:
        """ownsSlot (Server.scala:662-686): the leader owns everything
        below the delegation watermark plus its stripe; delegates own
        their stripe above it."""
        if not self.is_delegate:
            return False
        position = self.delegates.index(self.index)
        in_stripe = slot >= self.delegate_start \
            and (slot - self.delegate_start) % len(self.delegates) \
            == position
        if self.is_leader:
            return slot < self.delegate_start or in_stripe
        return in_stripe

    def _delegate_addresses(self) -> list[Address]:
        return [self.config.server_addresses[i] for i in self.delegates]

    def _execute_log(self) -> None:
        while True:
            entry = self.log.get(self.executed_watermark)
            if entry is None or not entry.chosen:
                return
            slot = self.executed_watermark
            self.executed_watermark += 1
            value = entry.vote_value
            if isinstance(value, Noop):
                continue
            cid = value.command_id
            key = (cid.client_address, cid.client_pseudonym)
            cached = self.client_table.get(key)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(value.command)
                self.client_table[key] = (cid.client_id, result)
            # The delegate owning the slot replies (cheatsheet: delegate
            # sends ClientReply).
            if self._owns_slot(slot):
                self.send(cid.client_address,
                          ClientReply(command_id=cid, result=result))

    def _choose(self, slot: int, value: CommandOrNoop) -> None:
        """Mark ``slot`` chosen locally (choose, Server.scala:633-660)."""
        entry = self.log.get(slot)
        if entry is not None and entry.chosen:
            return
        self.log.put(slot, _LogEntry(vote_round=self.round,
                                     vote_value=value, chosen=True))
        self.pending_votes.pop(slot, None)
        self.pending_values.pop(slot, None)
        if self.is_delegate and slot == self.next_owned_slot:
            self._advance_owned_slot()
        self._execute_log()

    # --- proposing (delegate) ---------------------------------------------
    def _propose_single(self, slot: int, value: CommandOrNoop) -> None:
        """Vote for ``value`` in ``slot`` ourselves and send Phase2as to
        the other delegates (Server.scala:765-806)."""
        self.log.put(slot, _LogEntry(vote_round=self.round,
                                     vote_value=value))
        self.pending_values[slot] = value
        self.pending_votes[slot] = {self.index}
        phase2a = Phase2a(slot=slot, round=self.round, value=value)
        for i in self.delegates:
            if i != self.index:
                self.send(self.config.server_addresses[i], phase2a)

    def _propose(self, slot: int, value: CommandOrNoop) -> None:
        """Noop-fill the unfilled slots just before ``slot`` so a slow
        delegate can't stall the log, then propose ``value``
        (proposeCommandOrNoop, Server.scala:808-855)."""
        for previous in range(max(self.delegate_start,
                                  slot - len(self.delegates) + 1), slot):
            if self.log.get(previous) is None:
                self._propose_single(previous, NOOP)
        self._propose_single(slot, value)

    # --- round change (leader) --------------------------------------------
    def start_round_change(self, new_round: int) -> None:
        """Become leader of ``new_round`` (Phase1 across servers)."""
        self.round = new_round
        self.in_phase1 = True
        self.phase1bs = {}
        self.pending_votes.clear()
        self.pending_values.clear()
        phase1a = Phase1a(round=new_round,
                          chosen_watermark=self.executed_watermark)
        for server in self.config.server_addresses:
            self.send(server, phase1a)

    def _maybe_change_leader(self) -> None:
        """leaderChangeTimer (Server.scala:500-527): if a delegate looks
        dead, run Phase1 in our own next round."""
        if self.heartbeat is None:
            return
        alive = self.heartbeat.unsafe_alive()
        alive.add(self.heartbeat_addresses[self.index])
        delegate_hbs = {self.heartbeat_addresses[i] for i in self.delegates}
        if not delegate_hbs <= alive:
            self.start_round_change(
                self.round_system.next_classic_round(self.index,
                                                     self.round))

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        handlers = {
            ClientRequest: self._handle_client_request,
            Phase1a: self._handle_phase1a,
            Phase1b: self._handle_phase1b,
            Phase2a: self._handle_phase2a,
            Phase2b: self._handle_phase2b,
            Phase3a: self._handle_phase3a,
            Phase2aAny: self._handle_phase2a_any,
            Phase2aAnyAck: lambda s, m: None,
            Nack: self._handle_nack,
        }
        handler = handlers.get(type(message))
        if handler is None:
            self.logger.fatal(f"unexpected server message {message!r}")
        handler(src, message)

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        if request.round < self.round or not self.is_delegate \
                or self.in_phase1:
            # Stale client or not a delegate: only the leader answers with
            # RoundInfo (FasterPaxos.proto "Learning Who the Delegates
            # Are").
            if self.is_leader and not self.in_phase1:
                self.send(src, RoundInfo(round=self.round,
                                         delegates=self.delegates))
            return
        slot = self.next_owned_slot
        self._advance_owned_slot()
        self._propose(slot, request.command)

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round < self.round:
            self.send(src, Nack(round=self.round))
            return
        self._advance_round(phase1a.round)
        info = tuple(
            Phase1bSlotInfo(slot=slot, vote_round=entry.vote_round,
                            vote_value=entry.vote_value,
                            chosen=entry.chosen)
            for slot, entry in self.log.items(
                start=phase1a.chosen_watermark))
        self.send(src, Phase1b(server_index=self.index,
                               round=phase1a.round, info=info))

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not self.in_phase1 or phase1b.round != self.round:
            return
        self.phase1bs[phase1b.server_index] = phase1b
        if len(self.phase1bs) < self.config.f + 1:
            return
        self.in_phase1 = False
        max_slot = max((i.slot for p in self.phase1bs.values()
                        for i in p.info), default=-1)
        # Pick delegates (ourselves + f others, preferring ones the
        # heartbeat says are alive, pickDelegates Server.scala:609-617)
        # and hand them the suffix BEFORE re-proposing the repaired
        # prefix, so their votes land in delegate state.
        others = [i for i in range(len(self.config.server_addresses))
                  if i != self.index]
        if self.heartbeat is not None:
            alive = self.heartbeat.unsafe_alive()
            alive_others = [i for i in others
                            if self.heartbeat_addresses[i] in alive]
            if len(alive_others) >= self.config.f:
                others = alive_others
        self.delegates = tuple([self.index]
                               + sorted(self.rng.sample(others,
                                                        self.config.f)))
        # The delegate stripe must clear the chosen watermark, not just
        # the voted max: Phase1bs report nothing below
        # phase1a.chosen_watermark, so on a quiescent failover max_slot
        # is -1 and an unclamped start rewinds to 0 -- any delegate
        # with a hole below the watermark (it missed a Chosen while
        # partitioned) would then re-propose a FRESH command into an
        # already-chosen slot and commit it with f+1 delegate votes
        # (the PR 3 double-choose class; found by paxsafe SAFE903).
        start = max(max_slot + 1, self.executed_watermark)
        any_message = Phase2aAny(round=self.round,
                                 delegates=self.delegates,
                                 start_slot=start)
        for i in self.delegates:
            if i != self.index:
                self.send(self.config.server_addresses[i], any_message)
        self._set_delegate_slots(start)
        # Repair every seen slot (safeValue, Server.scala:860-940):
        # already-chosen values are chosen directly; everything else is
        # only *safe* and must go through Phase2 with the new delegates.
        for slot in range(self.executed_watermark, max_slot + 1):
            entry = self.log.get(slot)
            if entry is not None and entry.chosen:
                continue
            infos = [i for p in self.phase1bs.values()
                     for i in p.info if i.slot == slot]
            chosen = next((i for i in infos if i.chosen), None)
            if chosen is not None:
                self._choose(slot, chosen.vote_value)
                for server in self.config.server_addresses:
                    if server != self.address:
                        self.send(server, Phase3a(slot=slot,
                                                  value=chosen.vote_value))
                continue
            value = (max(infos, key=lambda i: i.vote_round).vote_value
                     if infos else NOOP)
            self._propose_single(slot, value)
        self._execute_log()

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            self.send(src, Nack(round=self.round))
            return
        self._advance_round(phase2a.round)
        entry = self.log.get(phase2a.slot)
        phase2b = Phase2b(server_index=self.index, slot=phase2a.slot,
                          round=phase2a.round)
        if entry is not None and entry.chosen:
            # Already chosen: skip the protocol, tell the sender.
            self.send(src, Phase3a(slot=phase2a.slot,
                                   value=entry.vote_value))
            return
        if entry is None or isinstance(entry.vote_value, Noop):
            # Nothing / noop voted: vote for the sender's value. (Re-
            # voting a command over our noop is safe and special to
            # Faster Paxos, Server.scala:1584-1605.) With f=1 both
            # delegates have now voted, so the value is chosen
            # (useF1Optimization, Server.scala:1562-1600).
            if self.config.f == 1 and self.options.use_f1_optimization:
                self._choose(phase2a.slot, phase2a.value)
            else:
                self.log.put(phase2a.slot,
                             _LogEntry(vote_round=phase2a.round,
                                       vote_value=phase2a.value))
                if phase2a.slot == self.next_owned_slot:
                    self._advance_owned_slot()
            self.send(src, phase2b)
            return
        # We already voted for a command.
        if isinstance(phase2a.value, Noop):
            # ackNoopsWithCommands (Server.scala:1613-1625): tell the
            # noop's proposer about our command (or stay silent).
            if self.options.ack_noops_with_commands:
                self.send(src, dataclasses.replace(
                    phase2b, command=entry.vote_value))
            return
        # Command meets command (case e). Within a round, slot ownership
        # makes the commands identical; across rounds a repair-window
        # re-proposal can differ, so record the vote in the newer round
        # like any Paxos acceptor before acking -- acking while keeping
        # the old vote would let a later Phase1 resurrect it.
        if phase2a.round > entry.vote_round:
            self.log.put(phase2a.slot,
                         _LogEntry(vote_round=phase2a.round,
                                   vote_value=phase2a.value))
        self.send(src, phase2b)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        if phase2b.round != self.round:
            return
        entry = self.log.get(phase2b.slot)
        if entry is not None and entry.chosen:
            return
        voters = self.pending_votes.get(phase2b.slot)
        if voters is None:
            return
        pending = self.pending_values[phase2b.slot]
        # processPhase2b's case table (Server.scala:1060-1096).
        if isinstance(pending, Command) and phase2b.command is None \
                and not self._owns_slot(phase2b.slot):
            # Case (c): this Phase2b is for the noop we proposed before
            # we switched to the command; it doesn't count.
            return
        if isinstance(pending, Noop) and phase2b.command is not None:
            if phase2b.slot < self.delegate_start:
                # Case (f) is UNSOUND for Phase1 REPAIR re-proposals
                # (every pending slot below the delegation stripe):
                # this noop is the safe value computed from the read
                # quorum, so it may already be CHOSEN at servers
                # outside that quorum, and the reported command rides
                # an OLDER-round vote that must not count toward a
                # current-round quorum. Switching here let a noop
                # chosen in round r be overwritten by a command in
                # round r' > r (chosen-uniqueness violation; found by
                # the full-scale soak, seed 412 -- regression test in
                # tests/protocols/test_fasterpaxos.py). Ignoring the
                # ack stalls only this slot until a delegation that
                # includes a server that saw the choice; the fresh-
                # stripe switch below stays sound because quorum
                # intersection proves no chosen value can hide above
                # Phase1's max_slot.
                return
            # Case (f): our noop lost to a command; start counting
            # command votes (ours + the sender's).
            value: CommandOrNoop = phase2b.command
            self.log.put(phase2b.slot,
                         _LogEntry(vote_round=phase2b.round,
                                   vote_value=value))
            self.pending_values[phase2b.slot] = value
            voters = {self.index, phase2b.server_index}
            self.pending_votes[phase2b.slot] = voters
        else:
            # Cases (a), (d), (e): count the vote.
            voters.add(phase2b.server_index)
        # All f+1 delegates voting forms a classic quorum.
        if len(voters) < len(self.delegates):
            return
        value = self.pending_values[phase2b.slot]
        self._choose(phase2b.slot, value)
        for server in self.config.server_addresses:
            if server != self.address:
                self.send(server, Phase3a(slot=phase2b.slot, value=value))

    def _handle_phase3a(self, src: Address, phase3a: Phase3a) -> None:
        self._choose(phase3a.slot, phase3a.value)

    def _handle_phase2a_any(self, src: Address,
                            message: Phase2aAny) -> None:
        if message.round < self.round:
            self.send(src, Nack(round=self.round))
            return
        # Clears any stale in_phase1/delegate state on a round advance.
        self._advance_round(message.round)
        # Idempotent on duplicates: re-clearing pending votes for the
        # same delegation would drop in-flight vote counts.
        if (message.delegates != self.delegates
                or self.delegate_start != message.start_slot):
            self.delegates = message.delegates
            self.pending_votes.clear()
            self.pending_values.clear()
            if self.is_delegate:
                self._set_delegate_slots(message.start_slot)
        self.send(src, Phase2aAnyAck(server_index=self.index,
                                     round=message.round))

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        if nack.round <= self.round:
            return
        # Take over in a round we own above the nack.
        self.start_round_change(
            self.round_system.next_classic_round(self.index, nack.round))


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class FasterPaxosClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: FasterPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.round = 0
        self.delegates: tuple[int, ...] = tuple(range(config.f + 1))
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def _send_request(self, request: ClientRequest) -> None:
        delegate = self.delegates[self.rng.randrange(len(self.delegates))]
        self.send(self.config.server_addresses[delegate],
                  dataclasses.replace(request, round=self.round))

    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(self.round, Command(
            CommandId(self.address, pseudonym, id), command))
        self._send_request(request)

        def resend():
            # Broadcast to rediscover the round if we're stale.
            for server in self.config.server_addresses:
                self.send(server, dataclasses.replace(request,
                                                      round=self.round))
            timer.start()

        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientReply):
            pending = self.pending.get(message.command_id.client_pseudonym)
            if pending is None \
                    or pending.id != message.command_id.client_id:
                return
            pending.resend.stop()
            del self.pending[message.command_id.client_pseudonym]
            pending.callback(message.result)
        elif isinstance(message, RoundInfo):
            if message.round >= self.round:
                self.round = message.round
                self.delegates = message.delegates
        else:
            self.logger.fatal(f"unexpected client message {message!r}")

# Importing registers the steady-state binary codecs with the hybrid
# serializer (see fasterpaxos_wire.py).
from frankenpaxos_tpu.protocols import fasterpaxos_wire  # noqa: E402,F401
