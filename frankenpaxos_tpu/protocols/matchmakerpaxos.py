"""MatchmakerPaxos: single-decree Paxos with matchmade configurations.

Reference behavior: matchmakerpaxos/ (Leader.scala:35-560,
Matchmaker.scala:32-200, Acceptor.scala:30-210, Config.scala). A leader
is free to pick ANY quorum system of acceptors per round; 2f+1
matchmakers store the per-round configurations. To run round r the
leader:

  1. Matchmaking: sends its chosen quorum system to the matchmakers; a
     quorum of f+1 MatchReplies returns every configuration adopted in
     earlier rounds (monotone: a matchmaker nacks rounds <= its largest).
  2. Phase1: reads a read quorum of EVERY pending earlier configuration
     (the union of one read quorum per round), adopting the
     highest-vote-round value found.
  3. Phase2: writes a write quorum of its own configuration.

The per-round quorum systems are exactly the "quorum-matrix reshape"
shape that ops/quorum.py's MultiConfigQuorumChecker evaluates batched on
device (each checked row selects its configuration's padded mask plane).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.quorums import (
    quorum_system_from_dict,
    quorum_system_to_dict,
    QuorumSystem,
    SimpleMajority,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class MatchmakerPaxosConfig:
    f: int
    leader_addresses: tuple
    matchmaker_addresses: tuple
    acceptor_addresses: tuple

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.matchmaker_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 matchmakers")
        if len(self.acceptor_addresses) < self.f + 1:
            raise ValueError("need >= f+1 acceptors")


@dataclasses.dataclass(frozen=True)
class AcceptorGroup:
    round: int
    quorum_system: dict  # wire form of a QuorumSystem over acceptor indices


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    v: str


@dataclasses.dataclass(frozen=True)
class ClientReply:
    chosen: str


@dataclasses.dataclass(frozen=True)
class MatchRequest:
    acceptor_group: AcceptorGroup


@dataclasses.dataclass(frozen=True)
class MatchReply:
    round: int
    matchmaker_index: int
    acceptor_groups: tuple[AcceptorGroup, ...]


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int


@dataclasses.dataclass(frozen=True)
class Phase1bVote:
    vote_round: int
    vote_value: str


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    acceptor_index: int
    vote: Optional[Phase1bVote]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    round: int
    value: str


@dataclasses.dataclass(frozen=True)
class Phase2b:
    round: int
    acceptor_index: int


@dataclasses.dataclass(frozen=True)
class MatchmakerNack:
    round: int


@dataclasses.dataclass(frozen=True)
class AcceptorNack:
    round: int


@dataclasses.dataclass
class _Matchmaking:
    v: str
    quorum_system: QuorumSystem
    match_replies: dict[int, MatchReply]


@dataclasses.dataclass
class _Phase1:
    v: str
    quorum_system: QuorumSystem
    previous_quorum_systems: dict[int, QuorumSystem]
    acceptor_to_rounds: dict[int, set[int]]
    pending_rounds: set[int]
    phase1bs: dict[int, Phase1b]


@dataclasses.dataclass
class _Phase2:
    v: str
    quorum_system: QuorumSystem
    phase2bs: dict[int, Phase2b]


@dataclasses.dataclass
class _Chosen:
    v: str


class MatchmakerPaxosLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerPaxosConfig,
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.index = list(config.leader_addresses).index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = -1
        self.state: object = None  # Inactive
        self.waiting_clients: list[Address] = []

    def _random_quorum_system(self) -> QuorumSystem:
        """A random f+1 subset under simple majorities
        (Config.scala comment: any quorum system works)."""
        indices = self.rng.sample(range(len(self.config.acceptor_addresses)),
                                  self.config.f + 1)
        return SimpleMajority(indices)

    def _start_matchmaking(self, new_round: int, v: str) -> None:
        self.round = new_round
        quorum_system = self._random_quorum_system()
        request = MatchRequest(AcceptorGroup(
            round=self.round,
            quorum_system=quorum_system_to_dict(quorum_system)))
        for matchmaker in self.config.matchmaker_addresses:
            self.send(matchmaker, request)
        self.state = _Matchmaking(v=v, quorum_system=quorum_system,
                                  match_replies={})

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, MatchReply):
            self._handle_match_reply(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, (MatchmakerNack, AcceptorNack)):
            self._handle_nack(message.round)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        if isinstance(self.state, _Chosen):
            self.send(src, ClientReply(chosen=self.state.v))
            return
        # Clients force liveness by restarting the protocol
        # (Leader.scala:279-318).
        self.round = self.round_system.next_classic_round(self.index,
                                                          self.round)
        self._start_matchmaking(self.round, request.v)
        self.waiting_clients.append(src)

    def _handle_match_reply(self, src: Address, reply: MatchReply) -> None:
        if not isinstance(self.state, _Matchmaking):
            return
        state = self.state
        if reply.round != self.round:
            self.logger.check_lt(reply.round, self.round)
            return
        state.match_replies[reply.matchmaker_index] = reply
        if len(state.match_replies) < self.config.quorum_size:
            return

        # Collect every configuration from earlier rounds; we must read a
        # read quorum of each (Leader.scala:321-446).
        pending_rounds: set[int] = set()
        previous: dict[int, QuorumSystem] = {}
        acceptor_indices: set[int] = set()
        acceptor_to_rounds: dict[int, set[int]] = {}
        for r in state.match_replies.values():
            for group in r.acceptor_groups:
                pending_rounds.add(group.round)
                qs = quorum_system_from_dict(group.quorum_system)
                previous[group.round] = qs
                acceptor_indices |= qs.random_read_quorum(self.rng)
                for idx in qs.nodes():
                    acceptor_to_rounds.setdefault(idx, set()).add(group.round)

        if not pending_rounds:
            # Nothing was ever configured before: go straight to phase 2.
            self._start_phase2(state.v, state.quorum_system)
            return
        for idx in acceptor_indices:
            self.send(self.config.acceptor_addresses[idx],
                      Phase1a(round=self.round))
        self.state = _Phase1(
            v=state.v, quorum_system=state.quorum_system,
            previous_quorum_systems=previous,
            acceptor_to_rounds=acceptor_to_rounds,
            pending_rounds=pending_rounds, phase1bs={})

    def _start_phase2(self, v: str, quorum_system: QuorumSystem) -> None:
        for idx in quorum_system.random_write_quorum(self.rng):
            self.send(self.config.acceptor_addresses[idx],
                      Phase2a(round=self.round, value=v))
        self.state = _Phase2(v=v, quorum_system=quorum_system, phase2bs={})

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1):
            return
        state = self.state
        if phase1b.round != self.round:
            self.logger.check_lt(phase1b.round, self.round)
            return
        state.phase1bs[phase1b.acceptor_index] = phase1b
        # A round stops pending once a read quorum of its configuration
        # responded.
        for r in list(state.acceptor_to_rounds.get(phase1b.acceptor_index,
                                                   ())):
            if r in state.pending_rounds and state.previous_quorum_systems[
                    r].is_superset_of_read_quorum(set(state.phase1bs)):
                state.pending_rounds.discard(r)
        if state.pending_rounds:
            return
        votes = [p.vote for p in state.phase1bs.values()
                 if p.vote is not None]
        v = (state.v if not votes
             else max(votes, key=lambda vote: vote.vote_round).vote_value)
        self._start_phase2(v, state.quorum_system)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        if not isinstance(self.state, _Phase2):
            return
        state = self.state
        if phase2b.round != self.round:
            self.logger.check_lt(phase2b.round, self.round)
            return
        state.phase2bs[phase2b.acceptor_index] = phase2b
        if not state.quorum_system.is_superset_of_write_quorum(
                set(state.phase2bs)):
            return
        for client in self.waiting_clients:
            self.send(client, ClientReply(chosen=state.v))
        self.waiting_clients.clear()
        self.state = _Chosen(v=state.v)

    def _handle_nack(self, nack_round: int) -> None:
        if nack_round <= self.round or self.state is None \
                or isinstance(self.state, _Chosen):
            return
        self.round = self.round_system.next_classic_round(self.index,
                                                          nack_round)
        self._start_matchmaking(self.round, self.state.v)


class Matchmaker(Actor):
    """Stores per-round configurations; replies with all earlier ones
    (Matchmaker.scala:120-180). Monotone: nacks rounds <= the largest
    seen."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.matchmaker_addresses).index(address)
        self.acceptor_groups: dict[int, AcceptorGroup] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, MatchRequest):
            self.logger.fatal(f"unexpected matchmaker message {message!r}")
        round = message.acceptor_group.round
        if self.acceptor_groups and round <= max(self.acceptor_groups):
            self.send(src, MatchmakerNack(round=max(self.acceptor_groups)))
            return
        self.send(src, MatchReply(
            round=round, matchmaker_index=self.index,
            acceptor_groups=tuple(
                self.acceptor_groups[r]
                for r in sorted(self.acceptor_groups))))
        self.acceptor_groups[round] = message.acceptor_group


class MatchmakerPaxosAcceptor(Actor):
    """(matchmakerpaxos/Acceptor.scala:30-210)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[str] = None

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            if message.round < self.round:
                self.send(src, AcceptorNack(round=self.round))
                return
            self.round = message.round
            vote = (Phase1bVote(self.vote_round, self.vote_value)
                    if self.vote_value is not None else None)
            self.send(src, Phase1b(round=message.round,
                                   acceptor_index=self.index, vote=vote))
        elif isinstance(message, Phase2a):
            if message.round < self.round:
                self.send(src, AcceptorNack(round=self.round))
                return
            self.round = message.round
            self.vote_round = message.round
            self.vote_value = message.value
            self.send(src, Phase2b(round=message.round,
                                   acceptor_index=self.index))
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")


class MatchmakerPaxosClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MatchmakerPaxosConfig,
                 repropose_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.proposed_value: Optional[str] = None
        self.chosen_value: Optional[str] = None
        self.callbacks: list[Callable[[str], None]] = []
        self.repropose_timer = self.timer("repropose", repropose_period_s,
                                          self._repropose)

    def propose(self, v: str,
                callback: Optional[Callable[[str], None]] = None) -> None:
        if callback is not None:
            self.callbacks.append(callback)
        if self.chosen_value is not None:
            self._deliver()
            return
        if self.proposed_value is not None:
            return
        self.proposed_value = v
        self._send()
        self.repropose_timer.start()

    def _send(self) -> None:
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))]
        self.send(leader, ClientRequest(v=self.proposed_value))

    def _repropose(self) -> None:
        if self.chosen_value is None and self.proposed_value is not None:
            self._send()
            self.repropose_timer.start()

    def _deliver(self) -> None:
        for cb in self.callbacks:
            cb(self.chosen_value)
        self.callbacks.clear()

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        if self.chosen_value is None:
            self.chosen_value = message.chosen
            self.repropose_timer.stop()
        self._deliver()


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
