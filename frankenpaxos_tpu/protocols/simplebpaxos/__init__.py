"""Simple BPaxos: disaggregated generalized consensus.

Reference behavior: simplebpaxos/ (~2,200 LoC Scala; SURVEY.md section
2.2). Leaders assign vertices and ask a dependency-service quorum for
conflicts; per-vertex Paxos (proposers + acceptors) chooses
(command, deps); replicas execute in dependency-graph SCC order.
"""

from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    SimpleBPaxosConfig,
    VertexId,
    VertexIdPrefixSet,
)
from frankenpaxos_tpu.protocols.simplebpaxos.replica import (
    BPaxosClient,
    BPaxosReplica,
)
from frankenpaxos_tpu.protocols.simplebpaxos.roles import (
    BPaxosAcceptor,
    BPaxosDepServiceNode,
    BPaxosLeader,
    BPaxosProposer,
)

__all__ = [
    "BPaxosAcceptor",
    "BPaxosClient",
    "BPaxosDepServiceNode",
    "BPaxosLeader",
    "BPaxosProposer",
    "BPaxosReplica",
    "SimpleBPaxosConfig",
    "VertexId",
    "VertexIdPrefixSet",
]

# Importing registers the BPaxos binary codecs with the hybrid
# serializer (shared by SimpleGcBPaxos; see wire.py for the layout).
from frankenpaxos_tpu.protocols.simplebpaxos import wire  # noqa: E402,F401
