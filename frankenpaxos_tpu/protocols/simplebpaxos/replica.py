"""SimpleBPaxos Replica and Client.

Reference behavior: simplebpaxos/Replica.scala:33-430 (commit vertices
into the dependency graph, SCC-ordered execution, ClientTable
exactly-once, recover-vertex timers -> Recover to the vertex's
proposer), simplebpaxos/Client.scala.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.clienttable import ClientTable, NOT_EXECUTED
from frankenpaxos_tpu.depgraph import make_dependency_graph
from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    ClientReply,
    ClientRequest,
    Command,
    Commit,
    Noop,
    Recover,
    SimpleBPaxosConfig,
    VertexId,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine


@dataclasses.dataclass
class _Committed:
    command_or_noop: object
    dependencies: object


class BPaxosReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: SimpleBPaxosConfig,
                 state_machine: StateMachine,
                 execute_graph_batch_size: int = 1,
                 recover_vertex_min_period_s: float = 10.0,
                 recover_vertex_max_period_s: float = 20.0,
                 num_blockers: Optional[int] = 1, seed: int = 0,
                 dependency_graph: str = "tarjan"):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.execute_graph_batch_size = execute_graph_batch_size
        self.recover_min = recover_vertex_min_period_s
        self.recover_max = recover_vertex_max_period_s
        self.num_blockers = num_blockers
        self.index = list(config.replica_addresses).index(address)
        self.commands: dict[VertexId, _Committed] = {}
        self.dependency_graph = make_dependency_graph(
            dependency_graph,
            num_leaders=len(config.leader_addresses), make=VertexId)
        self.client_table: ClientTable = ClientTable()
        self.recover_vertex_timers: dict[VertexId, object] = {}
        self.num_pending = 0
        self.executed_count = 0

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, Commit):
            self.logger.fatal(f"unexpected replica message {message!r}")
        vertex_id = message.vertex_id
        if vertex_id in self.commands \
                or vertex_id in self.dependency_graph.executed:
            return
        self.commands[vertex_id] = _Committed(message.command_or_noop,
                                              message.dependencies)
        timer = self.recover_vertex_timers.pop(vertex_id, None)
        if timer is not None:
            timer.stop()
        self.dependency_graph.commit(
            vertex_id, 0,
            self._unexecuted_dependencies(message.dependencies))
        self.num_pending += 1
        if self.num_pending % self.execute_graph_batch_size == 0:
            self._execute_graph()
            self.num_pending = 0

    def _unexecuted_dependencies(self, dependencies):
        """Iterable of dependencies to hand the graph. Subclasses that
        track an executed-vertex set subtract it here so snapshot-sized
        dependency sets don't materialize the whole history."""
        return dependencies.materialize()

    def _execute_graph(self) -> None:
        executables, blockers = self.dependency_graph.execute(
            self.num_blockers)
        for blocked in blockers:
            if blocked not in self.recover_vertex_timers:
                self.recover_vertex_timers[blocked] = \
                    self._make_recover_timer(blocked)
        for vertex_id in executables:
            committed = self.commands.get(vertex_id)
            if committed is None:
                self.logger.fatal(f"{vertex_id} executable but unknown")
            self._execute(vertex_id, committed.command_or_noop)

    def _make_recover_timer(self, vertex_id: VertexId) -> object:
        def fire():
            # Ask the vertex's proposer to get it chosen (a noop if
            # nothing was proposed).
            self.send(self.config.proposer_addresses[
                vertex_id.replica_index % len(
                    self.config.proposer_addresses)],
                Recover(vertex_id=vertex_id))
            timer.start()

        timer = self.timer(f"recoverVertex {vertex_id}",
                           self.rng.uniform(self.recover_min,
                                            self.recover_max), fire)
        timer.start()
        return timer

    def _execute(self, vertex_id: VertexId, value) -> None:
        if isinstance(value, Noop):
            return
        command: Command = value
        identity = (command.client_address, command.client_pseudonym)
        if self.client_table.executed(identity,
                                      command.client_id) is not NOT_EXECUTED:
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        self.executed_count += 1
        # Replies are distributed round-robin over replicas so only one
        # replica replies (Replica.scala:330-360).
        num_replicas = len(self.config.replica_addresses)
        if vertex_id.instance_number % num_replicas == self.index:
            self.send(command.client_address, ClientReply(
                client_pseudonym=command.client_pseudonym,
                client_id=command.client_id, result=output))


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class BPaxosClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: SimpleBPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def propose(self, pseudonym: int, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(self.address, pseudonym, id,
                                        command))
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))]
        self.send(leader, request)

        def resend():
            target = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))]
            self.send(target, request)
            timer.start()

        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.client_pseudonym)
        if pending is None or pending.id != message.client_id:
            return
        pending.resend.stop()
        del self.pending[message.client_pseudonym]
        pending.callback(message.result)
