"""SimpleBPaxos Leader, DepServiceNode, Proposer, and Acceptor.

Reference behavior: simplebpaxos/Leader.scala:26-280 (assign vertex, ask
dep service quorum, union deps, hand to proposer),
DepServiceNode.scala:27-230 (conflict-index lookup with per-vertex
cache), Proposer.scala:24-540 (per-vertex Paxos with round-0 phase-1
skip, vertex-rotated round robin, nack -> higher-round phase 1, noop
recovery), Acceptor.scala:22-200 (per-vertex (round, vote) state).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    ClientRequest,
    Commit,
    DependencyReply,
    DependencyRequest,
    Nack,
    NOOP,
    Noop,
    Phase1a,
    Phase1b,
    Phase2a,
    Phase2b,
    Propose,
    Recover,
    SimpleBPaxosConfig,
    VertexId,
    VertexIdPrefixSet,
    VoteValue,
)
from frankenpaxos_tpu.roundsystem import RotatedClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils.topk import TUPLE_VERTEX_LIKE

VERTEX_LIKE = TUPLE_VERTEX_LIKE


class BPaxosLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: SimpleBPaxosConfig,
                 resend_deps_period_s: float = 10.0, seed: int = 0,
                 dep_backend: str = "host"):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_deps_period_s = resend_deps_period_s
        self.index = list(config.leader_addresses).index(address)
        self.next_vertex_id = 0
        # "host": per-reply VertexIdPrefixSet add_all loops. "tpu": the
        # dep-service quorum union as one batched ops/depset reduction
        # (VertexIdPrefixSet IS InstancePrefixSet, so the EPaxos
        # device_deps bridge applies unchanged).
        self.dep_backend = dep_backend
        # vertex -> ("waiting", command, {node_index: reply}, timer)
        #         | ("proposed",)
        self.states: dict[VertexId, object] = {}

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, DependencyReply):
            self._handle_dependency_reply(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        self._start_vertex(request.command)

    def _start_vertex(self, command) -> VertexId:
        """Allocate a vertex for ``command`` and ask a dep-service
        quorum for its dependencies (Leader.scala:120-180). Subclasses
        reuse this for non-client proposals (snapshot vertices)."""
        vertex_id = VertexId(self.index, self.next_vertex_id)
        self.next_vertex_id += 1
        dep_request = DependencyRequest(vertex_id=vertex_id,
                                        command=command)
        targets = list(self.config.dep_service_node_addresses)[
            :self.config.quorum_size]
        self.broadcast(targets, dep_request)

        def resend():
            self.broadcast(self.config.dep_service_node_addresses,
                           dep_request)
            timer.start()

        timer = self.timer(f"resendDeps {vertex_id}",
                           self.resend_deps_period_s, resend)
        timer.start()
        self.states[vertex_id] = ["waiting", command, {}, timer]
        return vertex_id

    def _handle_dependency_reply(self, src: Address,
                                 reply: DependencyReply) -> None:
        state = self.states.get(reply.vertex_id)
        if not (isinstance(state, list) and state[0] == "waiting"):
            self.logger.debug(f"DependencyReply for {reply.vertex_id} "
                              f"ignored")
            return
        state[2][reply.dep_service_node_index] = reply
        if len(state[2]) < self.config.quorum_size:
            return
        if self.dep_backend == "tpu":
            from frankenpaxos_tpu.protocols.epaxos import device_deps
            dependencies = device_deps.union_many(
                [r.dependencies for r in state[2].values()],
                len(self.config.leader_addresses),
                metrics=self.transport.runtime_metrics)
        else:
            dependencies = VertexIdPrefixSet(
                len(self.config.leader_addresses))
            for r in state[2].values():
                dependencies.add_all(r.dependencies)
        state[3].stop()
        self.send(self.config.proposer_addresses[self.index],
                  Propose(vertex_id=reply.vertex_id, command=state[1],
                          dependencies=dependencies))
        self.states[reply.vertex_id] = ("proposed",)


class BPaxosDepServiceNode(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: SimpleBPaxosConfig,
                 state_machine: StateMachine, top_k: int = 1):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.dep_service_node_addresses).index(address)
        self.conflict_index = state_machine.top_k_conflict_index(
            top_k, len(config.leader_addresses), VERTEX_LIKE)
        self.top_k = top_k
        # Deps must be deterministic per vertex across re-asks
        # (DepServiceNode.scala:130-136).
        self.dependencies_cache: dict[VertexId, VertexIdPrefixSet] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, DependencyRequest):
            self.logger.fatal(f"unexpected dep service message {message!r}")
        vertex_id = message.vertex_id
        dependencies = self.dependencies_cache.get(vertex_id)
        if dependencies is None:
            dependencies = self._compute_dependencies(vertex_id,
                                                      message.command)
            self.dependencies_cache[vertex_id] = dependencies
        self.send(src, DependencyReply(
            vertex_id=vertex_id, dep_service_node_index=self.index,
            dependencies=dependencies.copy()))

    def _compute_dependencies(self, vertex_id: VertexId,
                              command) -> VertexIdPrefixSet:
        """Conflict-index lookup for a new vertex; cached by receive so
        re-asks are deterministic. Subclasses extend (snapshot deps)."""
        payload = command.command
        if self.top_k == 1:
            dependencies = VertexIdPrefixSet.from_top_one(
                self.conflict_index.get_top_one_conflicts(payload))
        else:
            dependencies = VertexIdPrefixSet.from_top_k(
                self.conflict_index.get_top_k_conflicts(payload))
        dependencies.subtract_one(vertex_id)
        self.conflict_index.put(vertex_id, payload)
        return dependencies


@dataclasses.dataclass
class _Phase1State:
    round: int
    value: VoteValue
    phase1bs: dict[int, Phase1b]
    resend: object


@dataclasses.dataclass
class _Phase2State:
    round: int
    value: VoteValue
    phase2bs: dict[int, Phase2b]
    resend: object


@dataclasses.dataclass
class _ChosenState:
    value: VoteValue


class BPaxosProposer(Actor):
    """Per-vertex consensus. The round system is rotated so the vertex's
    own leader owns round 0 and can skip phase 1
    (Proposer.scala:151-216)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: SimpleBPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.index = list(config.proposer_addresses).index(address)
        self.states: dict[VertexId, object] = {}

    def _round_system(self, vertex_id: VertexId):
        return RotatedClassicRoundRobin(len(self.config.leader_addresses),
                                        vertex_id.replica_index)

    def _make_resend_timer(self, name: str, message) -> object:
        def resend():
            self.broadcast(self.config.acceptor_addresses, message)
            timer.start()

        timer = self.timer(name, self.resend_period_s, resend)
        timer.start()
        return timer

    def _propose_impl(self, vertex_id: VertexId, command_or_noop,
                      dependencies: VertexIdPrefixSet) -> None:
        if vertex_id in self.states:
            self.logger.debug(f"already proposing {vertex_id}")
            return
        value = VoteValue(command_or_noop, dependencies)
        round = self._round_system(vertex_id).next_classic_round(
            self.index, -1)
        targets = list(self.config.acceptor_addresses)[
            :self.config.quorum_size]
        if round == 0:
            phase2a = Phase2a(vertex_id=vertex_id, round=round,
                              vote_value=value)
            self.broadcast(targets, phase2a)
            self.states[vertex_id] = _Phase2State(
                round, value, {},
                self._make_resend_timer(f"resendPhase2a {vertex_id}",
                                        phase2a))
        else:
            phase1a = Phase1a(vertex_id=vertex_id, round=round)
            self.broadcast(targets, phase1a)
            self.states[vertex_id] = _Phase1State(
                round, value, {},
                self._make_resend_timer(f"resendPhase1a {vertex_id}",
                                        phase1a))

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Propose):
            self._propose_impl(message.vertex_id, message.command,
                               message.dependencies)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Nack):
            self._handle_nack(src, message)
        elif isinstance(message, Recover):
            self._handle_recover(src, message)
        else:
            self.logger.fatal(f"unexpected proposer message {message!r}")

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        state = self.states.get(phase1b.vertex_id)
        if not isinstance(state, _Phase1State):
            return
        if phase1b.round != state.round:
            self.logger.check_lt(phase1b.round, state.round)
            return
        state.phase1bs[phase1b.acceptor_id] = phase1b
        if len(state.phase1bs) < self.config.quorum_size:
            return
        max_vote_round = max(r.vote_round for r in state.phase1bs.values())
        if max_vote_round == -1:
            proposal = state.value
        else:
            proposal = next(r.vote_value for r in state.phase1bs.values()
                            if r.vote_round == max_vote_round)
        phase2a = Phase2a(vertex_id=phase1b.vertex_id, round=state.round,
                          vote_value=proposal)
        self.broadcast(
            list(self.config.acceptor_addresses)[
                :self.config.quorum_size], phase2a)
        state.resend.stop()
        self.states[phase1b.vertex_id] = _Phase2State(
            state.round, proposal, {},
            self._make_resend_timer(f"resendPhase2a {phase1b.vertex_id}",
                                    phase2a))

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        state = self.states.get(phase2b.vertex_id)
        if not isinstance(state, _Phase2State):
            return
        if phase2b.round != state.round:
            self.logger.check_lt(phase2b.round, state.round)
            return
        state.phase2bs[phase2b.acceptor_id] = phase2b
        if len(state.phase2bs) < self.config.quorum_size:
            return
        state.resend.stop()
        self.states[phase2b.vertex_id] = _ChosenState(state.value)
        self.broadcast(self.config.replica_addresses, Commit(
            vertex_id=phase2b.vertex_id,
            command_or_noop=state.value.command_or_noop,
            dependencies=state.value.dependencies.copy()))

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        state = self.states.get(nack.vertex_id)
        if state is None or isinstance(state, _ChosenState):
            return
        if nack.higher_round <= state.round:
            return
        round = self._round_system(nack.vertex_id).next_classic_round(
            self.index, nack.higher_round)
        phase1a = Phase1a(vertex_id=nack.vertex_id, round=round)
        self.broadcast(
            list(self.config.acceptor_addresses)[
                :self.config.quorum_size], phase1a)
        state.resend.stop()
        self.states[nack.vertex_id] = _Phase1State(
            round, state.value, {},
            self._make_resend_timer(f"resendPhase1a {nack.vertex_id}",
                                    phase1a))

    def _handle_recover(self, src: Address, recover: Recover) -> None:
        state = self.states.get(recover.vertex_id)
        if state is None:
            self._propose_impl(recover.vertex_id, NOOP, VertexIdPrefixSet(
                len(self.config.leader_addresses)))
        elif isinstance(state, _ChosenState):
            self.send(src, Commit(
                vertex_id=recover.vertex_id,
                command_or_noop=state.value.command_or_noop,
                dependencies=state.value.dependencies.copy()))


@dataclasses.dataclass
class _AcceptorState:
    round: int = -1
    vote_round: int = -1
    vote_value: Optional[VoteValue] = None


class BPaxosAcceptor(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: SimpleBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.states: dict[VertexId, _AcceptorState] = {}

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            state = self.states.setdefault(message.vertex_id,
                                           _AcceptorState())
            if message.round < state.round:
                self.send(src, Nack(message.vertex_id, state.round))
                return
            state.round = message.round
            self.send(src, Phase1b(
                vertex_id=message.vertex_id, acceptor_id=self.index,
                round=message.round, vote_round=state.vote_round,
                vote_value=state.vote_value))
        elif isinstance(message, Phase2a):
            state = self.states.setdefault(message.vertex_id,
                                           _AcceptorState())
            if message.round < state.round:
                self.send(src, Nack(message.vertex_id, state.round))
                return
            state.round = message.round
            state.vote_round = message.round
            state.vote_value = message.vote_value
            self.send(src, Phase2b(vertex_id=message.vertex_id,
                                   acceptor_id=self.index,
                                   round=message.round))
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")
