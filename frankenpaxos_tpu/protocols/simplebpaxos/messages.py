"""SimpleBPaxos messages and config.

Reference behavior: simplebpaxos/SimpleBPaxos.proto, Config.scala.
Vertex ids are (leader_index, id); dependency sets are
VertexIdPrefixSets -- structurally identical to EPaxos InstancePrefixSets
(per-leader IntPrefixSet columns), which we reuse.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    Instance as VertexId,
    InstancePrefixSet as VertexIdPrefixSet,
)
from frankenpaxos_tpu.runtime.transport import Address


@dataclasses.dataclass(frozen=True)
class SimpleBPaxosConfig:
    f: int
    leader_addresses: tuple
    proposer_addresses: tuple
    dep_service_node_addresses: tuple
    acceptor_addresses: tuple
    replica_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.proposer_addresses) != len(self.leader_addresses):
            raise ValueError("proposers must mirror leaders")
        if len(self.dep_service_node_addresses) != self.n:
            raise ValueError("need 2f+1 dep service nodes")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError("need 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


@dataclasses.dataclass(frozen=True)
class Command:
    client_address: Address
    client_pseudonym: int
    client_id: int
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
CommandOrNoop = Union[Command, Noop]


@dataclasses.dataclass(frozen=True)
class VoteValue:
    command_or_noop: CommandOrNoop
    dependencies: VertexIdPrefixSet


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class DependencyRequest:
    vertex_id: VertexId
    command: Command


@dataclasses.dataclass(frozen=True)
class DependencyReply:
    vertex_id: VertexId
    dep_service_node_index: int
    dependencies: VertexIdPrefixSet


@dataclasses.dataclass(frozen=True)
class Propose:
    vertex_id: VertexId
    command: Command
    dependencies: VertexIdPrefixSet


@dataclasses.dataclass(frozen=True)
class Phase1a:
    vertex_id: VertexId
    round: int


@dataclasses.dataclass(frozen=True)
class Phase1b:
    vertex_id: VertexId
    acceptor_id: int
    round: int
    vote_round: int
    vote_value: Optional[VoteValue]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    vertex_id: VertexId
    round: int
    vote_value: VoteValue


@dataclasses.dataclass(frozen=True)
class Phase2b:
    vertex_id: VertexId
    acceptor_id: int
    round: int


@dataclasses.dataclass(frozen=True)
class Nack:
    vertex_id: VertexId
    higher_round: int


@dataclasses.dataclass(frozen=True)
class Commit:
    vertex_id: VertexId
    command_or_noop: CommandOrNoop
    dependencies: VertexIdPrefixSet


@dataclasses.dataclass(frozen=True)
class Recover:
    vertex_id: VertexId


@dataclasses.dataclass(frozen=True)
class ClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes
