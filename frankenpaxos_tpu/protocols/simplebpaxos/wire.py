"""Binary codecs for the SimpleBPaxos / SimpleGcBPaxos hot path.

The BPaxos command path (DependencyRequest -> DependencyReply ->
Propose -> Phase2a/Phase2b -> Commit, simplebpaxos/SimpleBPaxos.proto)
carries a VertexIdPrefixSet on most hops; its wire form reuses the
EPaxos column layout (``_put_deps``/``_take_deps`` in
protocols/epaxos/wire.py -- VertexIdPrefixSet IS InstancePrefixSet).
SimpleGcBPaxos shares these message types.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols.epaxos.wire import _put_deps, _take_deps
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    ClientReply,
    ClientRequest,
    Command,
    Commit,
    DependencyReply,
    DependencyRequest,
    Nack,
    NOOP,
    Noop,
    Phase1a,
    Phase1b,
    Phase2a,
    Phase2b,
    Propose,
    Recover,
    VertexId,
    VoteValue,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_VID = struct.Struct("<iq")  # (leader_index, id)


def _put_vertex(out: bytearray, vertex_id: VertexId) -> None:
    out += _VID.pack(vertex_id.replica_index, vertex_id.instance_number)


def _take_vertex(buf: bytes, at: int):
    leader, id = _VID.unpack_from(buf, at)
    return VertexId(leader, id), at + _VID.size


def _put_command(out: bytearray, command) -> None:
    """A Command, or (GcBPaxos) a sentinel like SnapshotMarker riding a
    pickled escape hatch."""
    if isinstance(command, Command):
        out.append(0)
        _put_address(out, command.client_address)
        out += _I64I64.pack(command.client_pseudonym, command.client_id)
        _put_bytes(out, command.command)
    else:
        from frankenpaxos_tpu.runtime import serializer

        out.append(1)
        _put_bytes(out, serializer.guarded_pickle_dumps(command, "command"))


def _take_command(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 1:
        from frankenpaxos_tpu.runtime import serializer

        raw, at = _take_bytes(buf, at)
        return serializer.guarded_pickle_loads(raw, "command"), at
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return Command(address, pseudonym, id, payload), at


def _put_vote_value(out: bytearray, value: VoteValue) -> None:
    if isinstance(value.command_or_noop, Noop):
        out.append(0)
    else:
        out.append(1)
        _put_command(out, value.command_or_noop)
    _put_deps(out, value.dependencies)


def _take_vote_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        command = NOOP
    else:
        command, at = _take_command(buf, at)
    deps, at = _take_deps(buf, at)
    return VoteValue(command, deps), at


class BPaxosClientRequestCodec(MessageCodec):
    message_type = ClientRequest
    tag = 21

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return ClientRequest(command), at


class DependencyRequestCodec(MessageCodec):
    message_type = DependencyRequest
    tag = 22

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        _put_command(out, message.command)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        command, at = _take_command(buf, at)
        return DependencyRequest(vertex_id, command), at


class DependencyReplyCodec(MessageCodec):
    message_type = DependencyReply
    tag = 23

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I32.pack(message.dep_service_node_index)
        _put_deps(out, message.dependencies)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (node,) = _I32.unpack_from(buf, at)
        deps, at = _take_deps(buf, at + 4)
        return DependencyReply(vertex_id, node, deps), at


class ProposeCodec(MessageCodec):
    message_type = Propose
    tag = 24

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        _put_command(out, message.command)
        _put_deps(out, message.dependencies)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        command, at = _take_command(buf, at)
        deps, at = _take_deps(buf, at)
        return Propose(vertex_id, command, deps), at


class BPaxosPhase2aCodec(MessageCodec):
    message_type = Phase2a
    tag = 25

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I64.pack(message.round)
        _put_vote_value(out, message.vote_value)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        value, at = _take_vote_value(buf, at + 8)
        return Phase2a(vertex_id, round, value), at


class BPaxosPhase2bCodec(MessageCodec):
    message_type = Phase2b
    tag = 26

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I64I64.pack(message.acceptor_id, message.round)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        acceptor, round = _I64I64.unpack_from(buf, at)
        return Phase2b(vertex_id, acceptor, round), at + 16


class BPaxosCommitCodec(MessageCodec):
    """Commit shares the command-or-noop + deps framing with
    VoteValue, so it reuses that codec pair."""

    message_type = Commit
    tag = 27

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        _put_vote_value(out, VoteValue(message.command_or_noop,
                                       message.dependencies))

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        value, at = _take_vote_value(buf, at)
        return Commit(vertex_id, value.command_or_noop,
                      value.dependencies), at


class BPaxosClientReplyCodec(MessageCodec):
    message_type = ClientReply
    tag = 28

    def encode(self, out, message):
        out += _I64I64.pack(message.client_pseudonym, message.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return ClientReply(pseudonym, id, result), at


# --- the recovery cold path (COD301 burn-down, extended tags 176-178) -------


class BPaxosPhase1aCodec(MessageCodec):
    message_type = Phase1a
    tag = 176

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        return Phase1a(vertex_id=vertex_id, round=round), at + 8


class BPaxosPhase1bCodec(MessageCodec):
    message_type = Phase1b
    tag = 177

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I32.pack(message.acceptor_id)
        out += _I64I64.pack(message.round, message.vote_round)
        if message.vote_value is None:
            out.append(0)
        else:
            out.append(1)
            _put_vote_value(out, message.vote_value)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (acceptor_id,) = _I32.unpack_from(buf, at)
        round, vote_round = _I64I64.unpack_from(buf, at + 4)
        present = buf[at + 20]
        at += 21
        vote_value = None
        if present:
            vote_value, at = _take_vote_value(buf, at)
        return Phase1b(vertex_id=vertex_id, acceptor_id=acceptor_id,
                       round=round, vote_round=vote_round,
                       vote_value=vote_value), at


class BPaxosNackCodec(MessageCodec):
    message_type = Nack
    tag = 178

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I64.pack(message.higher_round)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (higher_round,) = _I64.unpack_from(buf, at)
        return Nack(vertex_id=vertex_id,
                    higher_round=higher_round), at + 8


class BPaxosRecoverCodec(MessageCodec):
    """Hole recovery for a committed-but-unexecuted vertex (paxsim
    COD301 burn-down): per-hole traffic, but it is exactly what is on
    the wire while a replica catches up after a crash, and pickled
    frames are refused under ``set_pickle_fallback(False)``."""

    message_type = Recover
    tag = 200

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        return Recover(vertex_id=vertex_id), at


for _codec in (BPaxosClientRequestCodec(), DependencyRequestCodec(),
               DependencyReplyCodec(), ProposeCodec(),
               BPaxosPhase2aCodec(), BPaxosPhase2bCodec(),
               BPaxosCommitCodec(), BPaxosClientReplyCodec(),
               BPaxosPhase1aCodec(), BPaxosPhase1bCodec(),
               BPaxosNackCodec(), BPaxosRecoverCodec()):
    register_codec(_codec)

# Importing for side effect: registers the drain-coalesced DepReplyRun
# codec and its paxwire coalescer for tag 23.
from frankenpaxos_tpu.runs import wire as _run_wire  # noqa: E402,F401
