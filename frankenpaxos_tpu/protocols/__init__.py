"""Protocol implementations.

Each protocol package mirrors the reference's structure: message
dataclasses (the analog of the per-protocol ``.proto``), a ``Config``
listing all role addresses with a ``check_valid()``, and one Actor
subclass per role. Roles are pure single-threaded state machines over the
runtime contract; their hot loops call into the batched device kernels in
``ops/``.
"""
