"""Unanimous BPaxos: fast-path BPaxos with unanimous dependency quorums.

Reference behavior: unanimousbpaxos/ (Config.scala: fast quorum = n =
2f+1; Leader.scala:35-900, DepServiceNode.scala:25-185,
Acceptor.scala:21-280, Client.scala). Flow:

  * leader assigns a vertex and broadcasts DependencyRequest to all dep
    service nodes; dep node i computes conflicts and forwards a
    FastProposal(command, deps) to its colocated acceptor i, which votes
    in the implicit fast round 0 and replies Phase2bFast to the leader;
  * if all n acceptors voted identical dependencies, the value is chosen
    (the unanimous fast path); otherwise the leader performs coordinated
    recovery: it skips phase 1 and proposes the union of deps in round 1;
  * stuck vertices recover through classic phase 1/2 rounds;
  * leaders double as replicas: committed vertices execute locally in
    dependency-graph order and the owning leader replies.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

from frankenpaxos_tpu.clienttable import ClientTable, Executed, NOT_EXECUTED
from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.protocols.simplebpaxos.messages import (
    Command,
    NOOP,
    Noop,
    VertexId,
)
from frankenpaxos_tpu.roundsystem import RotatedClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine


@dataclasses.dataclass(frozen=True)
class UnanimousBPaxosConfig:
    f: int
    leader_addresses: tuple
    dep_service_node_addresses: tuple
    acceptor_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum_size(self) -> int:
        return self.f + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.n

    def check_valid(self) -> None:
        if len(self.leader_addresses) != self.f + 1:
            raise ValueError("need exactly f+1 leaders")
        if len(self.dep_service_node_addresses) != self.n:
            raise ValueError("need 2f+1 dep service nodes")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError("need 2f+1 acceptors")


@dataclasses.dataclass(frozen=True)
class VoteValue:
    command_or_noop: Union[Command, Noop]
    dependencies: frozenset


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes


@dataclasses.dataclass(frozen=True)
class DependencyRequest:
    vertex_id: VertexId
    command: Command


@dataclasses.dataclass(frozen=True)
class FastProposal:
    vertex_id: VertexId
    value: VoteValue


@dataclasses.dataclass(frozen=True)
class Phase2bFast:
    vertex_id: VertexId
    acceptor_id: int
    vote_value: VoteValue


@dataclasses.dataclass(frozen=True)
class Phase1a:
    vertex_id: VertexId
    round: int


@dataclasses.dataclass(frozen=True)
class Phase1b:
    vertex_id: VertexId
    acceptor_id: int
    round: int
    vote_round: int
    vote_value: Optional[VoteValue]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    vertex_id: VertexId
    round: int
    vote_value: VoteValue


@dataclasses.dataclass(frozen=True)
class Phase2bClassic:
    vertex_id: VertexId
    acceptor_id: int
    round: int


@dataclasses.dataclass(frozen=True)
class Nack:
    vertex_id: VertexId
    higher_round: int


@dataclasses.dataclass(frozen=True)
class Commit:
    vertex_id: VertexId
    value: VoteValue


@dataclasses.dataclass
class _Phase2Fast:
    command: Command
    phase2b_fasts: dict[int, Phase2bFast]
    resend: object


@dataclasses.dataclass
class _Phase1:
    round: int
    value: VoteValue
    phase1bs: dict[int, Phase1b]
    resend: object


@dataclasses.dataclass
class _Phase2Classic:
    round: int
    value: VoteValue
    phase2bs: dict[int, Phase2bClassic]
    resend: object


@dataclasses.dataclass
class _Committed:
    value: VoteValue


class UnanimousBPaxosLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: UnanimousBPaxosConfig,
                 state_machine: StateMachine,
                 resend_period_s: float = 10.0,
                 recover_min_period_s: float = 20.0,
                 recover_max_period_s: float = 40.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.recover_min = recover_min_period_s
        self.recover_max = recover_max_period_s
        self.index = list(config.leader_addresses).index(address)
        self.next_vertex_id = 0
        self.states: dict[VertexId, object] = {}
        self.dependency_graph = TarjanDependencyGraph()
        self.client_table: ClientTable = ClientTable()
        self.recover_vertex_timers: dict[VertexId, object] = {}
        self.executed_count = 0

    def _round_system(self, vertex_id: VertexId):
        # The vertex owner leads rounds 0 and 1 (coordinated recovery).
        return RotatedClassicRoundRobin(len(self.config.leader_addresses),
                                        vertex_id.replica_index)

    def _make_resend_timer(self, name: str, targets, message) -> object:
        def resend():
            for dst in targets:
                self.send(dst, message)
            timer.start()

        timer = self.timer(name, self.resend_period_s, resend)
        timer.start()
        return timer

    def _stop_timers(self, vertex_id: VertexId) -> None:
        state = self.states.get(vertex_id)
        if state is not None and hasattr(state, "resend"):
            state.resend.stop()

    # --- commit + execution ----------------------------------------------
    def _commit(self, vertex_id: VertexId, value: VoteValue,
                inform_others: bool) -> None:
        if isinstance(self.states.get(vertex_id), _Committed):
            return
        self._stop_timers(vertex_id)
        self.states[vertex_id] = _Committed(value)
        timer = self.recover_vertex_timers.pop(vertex_id, None)
        if timer is not None:
            timer.stop()
        if inform_others:
            for leader in self.config.leader_addresses:
                if leader != self.address:
                    self.send(leader, Commit(vertex_id, value))
        self.dependency_graph.commit(vertex_id, 0, set(value.dependencies))
        executables, blockers = self.dependency_graph.execute(1)
        for blocked in blockers:
            if blocked not in self.recover_vertex_timers:
                self.recover_vertex_timers[blocked] = \
                    self._make_recover_timer(blocked)
        for v in executables:
            committed = self.states.get(v)
            if not isinstance(committed, _Committed):
                self.logger.fatal(f"{v} executable but not committed")
            self._execute(v, committed.value)

    def _execute(self, vertex_id: VertexId, value: VoteValue) -> None:
        if isinstance(value.command_or_noop, Noop):
            return
        command = value.command_or_noop
        identity = (command.client_address, command.client_pseudonym)
        if self.client_table.executed(identity,
                                      command.client_id) is not NOT_EXECUTED:
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        self.executed_count += 1
        if vertex_id.replica_index == self.index:
            self.send(command.client_address, ClientReply(
                client_pseudonym=command.client_pseudonym,
                client_id=command.client_id, result=output))

    def _make_recover_timer(self, vertex_id: VertexId) -> object:
        def fire():
            self._recover_vertex(vertex_id)
            timer.start()

        timer = self.timer(f"recoverVertex {vertex_id}",
                           self.rng.uniform(self.recover_min,
                                            self.recover_max), fire)
        timer.start()
        return timer

    def _recover_vertex(self, vertex_id: VertexId) -> None:
        """Classic phase 1 in a round we own (Leader.scala:280-330)."""
        state = self.states.get(vertex_id)
        if isinstance(state, (_Committed, _Phase1, _Phase2Classic)):
            return
        round = self._round_system(vertex_id).next_classic_round(
            self.index, 1)
        self._stop_timers(vertex_id)
        phase1a = Phase1a(vertex_id=vertex_id, round=round)
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, phase1a)
        self.states[vertex_id] = _Phase1(
            round, VoteValue(NOOP, frozenset()), {},
            self._make_resend_timer(f"resendPhase1a {vertex_id}",
                                    self.config.acceptor_addresses,
                                    phase1a))

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, Phase2bFast):
            self._handle_phase2b_fast(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2bClassic):
            self._handle_phase2b_classic(src, message)
        elif isinstance(message, Nack):
            self._handle_nack(src, message)
        elif isinstance(message, Commit):
            self._commit(message.vertex_id, message.value,
                         inform_others=False)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        command = request.command
        identity = (command.client_address, command.client_pseudonym)
        executed = self.client_table.executed(identity, command.client_id)
        if isinstance(executed, Executed):
            if executed.output is not None:
                self.send(src, ClientReply(command.client_pseudonym,
                                           command.client_id,
                                           executed.output))
            return
        vertex_id = VertexId(self.index, self.next_vertex_id)
        self.next_vertex_id += 1
        dep_request = DependencyRequest(vertex_id, command)
        for node in self.config.dep_service_node_addresses:
            self.send(node, dep_request)
        self.states[vertex_id] = _Phase2Fast(
            command, {},
            self._make_resend_timer(
                f"resendDeps {vertex_id}",
                self.config.dep_service_node_addresses, dep_request))
        self.recover_vertex_timers[vertex_id] = \
            self._make_recover_timer(vertex_id)

    def _handle_phase2b_fast(self, src: Address,
                             phase2b: Phase2bFast) -> None:
        state = self.states.get(phase2b.vertex_id)
        if not isinstance(state, _Phase2Fast):
            return
        state.phase2b_fasts[phase2b.acceptor_id] = phase2b
        if len(state.phase2b_fasts) < self.config.fast_quorum_size:
            return
        deps_set = {v.vote_value.dependencies
                    for v in state.phase2b_fasts.values()}
        if len(deps_set) == 1:
            # Unanimous: fast-path commit.
            self._commit(phase2b.vertex_id,
                         VoteValue(state.command, next(iter(deps_set))),
                         inform_others=True)
            return
        # Coordinated recovery: skip phase 1, propose the union in round 1
        # (Leader.scala:660-695).
        union = frozenset().union(*deps_set)
        value = VoteValue(state.command, union)
        state.resend.stop()
        phase2a = Phase2a(phase2b.vertex_id, 1, value)
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, phase2a)
        self.states[phase2b.vertex_id] = _Phase2Classic(
            1, value, {},
            self._make_resend_timer(f"resendPhase2a {phase2b.vertex_id}",
                                    self.config.acceptor_addresses,
                                    phase2a))
        timer = self.recover_vertex_timers.pop(phase2b.vertex_id, None)
        if timer is not None:
            timer.stop()

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        state = self.states.get(phase1b.vertex_id)
        if not isinstance(state, _Phase1):
            return
        if phase1b.round != state.round:
            return
        state.phase1bs[phase1b.acceptor_id] = phase1b
        if len(state.phase1bs) < self.config.classic_quorum_size:
            return
        max_vote_round = max(r.vote_round for r in state.phase1bs.values())
        if max_vote_round >= 0:
            value = next(r.vote_value for r in state.phase1bs.values()
                         if r.vote_round == max_vote_round)
        else:
            value = VoteValue(NOOP, frozenset())
        state.resend.stop()
        phase2a = Phase2a(phase1b.vertex_id, state.round, value)
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, phase2a)
        self.states[phase1b.vertex_id] = _Phase2Classic(
            state.round, value, {},
            self._make_resend_timer(f"resendPhase2a {phase1b.vertex_id}",
                                    self.config.acceptor_addresses,
                                    phase2a))

    def _handle_phase2b_classic(self, src: Address,
                                phase2b: Phase2bClassic) -> None:
        state = self.states.get(phase2b.vertex_id)
        if not isinstance(state, _Phase2Classic):
            return
        if phase2b.round != state.round:
            return
        state.phase2bs[phase2b.acceptor_id] = phase2b
        if len(state.phase2bs) < self.config.classic_quorum_size:
            return
        self._commit(phase2b.vertex_id, state.value, inform_others=True)

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        state = self.states.get(nack.vertex_id)
        if state is None or isinstance(state, _Committed):
            return
        round = getattr(state, "round", 0)
        if nack.higher_round <= round:
            return
        new_round = self._round_system(nack.vertex_id).next_classic_round(
            self.index, nack.higher_round)
        self._stop_timers(nack.vertex_id)
        value = getattr(state, "value", None)
        if value is None:  # was Phase2Fast
            value = VoteValue(state.command, frozenset())
        phase1a = Phase1a(nack.vertex_id, new_round)
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, phase1a)
        self.states[nack.vertex_id] = _Phase1(
            new_round, value, {},
            self._make_resend_timer(f"resendPhase1a {nack.vertex_id}",
                                    self.config.acceptor_addresses, phase1a))


class UnanimousBPaxosDepServiceNode(Actor):
    """Computes deps and forwards a FastProposal to its colocated acceptor
    (DepServiceNode.scala:121-152)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: UnanimousBPaxosConfig,
                 state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.dep_service_node_addresses).index(address)
        self.conflict_index = state_machine.conflict_index()
        self.dependencies_cache: dict[VertexId, frozenset] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, DependencyRequest):
            self.logger.fatal(f"unexpected dep node message {message!r}")
        vertex_id = message.vertex_id
        dependencies = self.dependencies_cache.get(vertex_id)
        if dependencies is None:
            payload = message.command.command
            dependencies = frozenset(
                self.conflict_index.get_conflicts(payload)) - {vertex_id}
            self.conflict_index.put(vertex_id, payload)
            self.dependencies_cache[vertex_id] = dependencies
        self.send(self.config.acceptor_addresses[self.index],
                  FastProposal(vertex_id,
                               VoteValue(message.command, dependencies)))


@dataclasses.dataclass
class _AcceptorState:
    round: int = 0
    vote_round: int = -1
    vote_value: Optional[VoteValue] = None


class UnanimousBPaxosAcceptor(Actor):
    """(Acceptor.scala:21-280): implicit any in round 0."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: UnanimousBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.states: dict[VertexId, _AcceptorState] = {}

    def _leader_of(self, vertex_id: VertexId) -> Address:
        return self.config.leader_addresses[vertex_id.replica_index]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, FastProposal):
            self._handle_fast_proposal(src, message)
        elif isinstance(message, Phase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_fast_proposal(self, src: Address,
                              proposal: FastProposal) -> None:
        state = self.states.get(proposal.vertex_id)
        if state is None:
            self.states[proposal.vertex_id] = _AcceptorState(
                round=0, vote_round=0, vote_value=proposal.value)
            self.send(self._leader_of(proposal.vertex_id),
                      Phase2bFast(vertex_id=proposal.vertex_id,
                                  acceptor_id=self.index,
                                  vote_value=proposal.value))
        elif state.round > 0:
            self.send(self._leader_of(proposal.vertex_id),
                      Nack(proposal.vertex_id, state.round))
        # Already voted in round 0: ignore.

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        state = self.states.setdefault(phase1a.vertex_id, _AcceptorState())
        if phase1a.round < state.round:
            self.send(src, Nack(phase1a.vertex_id, state.round))
            return
        state.round = phase1a.round
        self.send(src, Phase1b(vertex_id=phase1a.vertex_id,
                               acceptor_id=self.index, round=phase1a.round,
                               vote_round=state.vote_round,
                               vote_value=state.vote_value))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        state = self.states.setdefault(phase2a.vertex_id, _AcceptorState())
        if phase2a.round < state.round:
            self.send(src, Nack(phase2a.vertex_id, state.round))
            return
        state.round = phase2a.round
        state.vote_round = phase2a.round
        state.vote_value = phase2a.vote_value
        self.send(src, Phase2bClassic(vertex_id=phase2a.vertex_id,
                                      acceptor_id=self.index,
                                      round=phase2a.round))


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class UnanimousBPaxosClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: UnanimousBPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def propose(self, pseudonym: int, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(self.address, pseudonym, id,
                                        command))
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))]
        self.send(leader, request)

        def resend():
            target = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))]
            self.send(target, request)
            timer.start()

        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.client_pseudonym)
        if pending is None or pending.id != message.client_id:
            return
        pending.resend.stop()
        del self.pending[message.client_pseudonym]
        pending.callback(message.result)

# Importing registers the UnanimousBPaxos binary codecs with the
# hybrid serializer (see unanimousbpaxos_wire.py).
from frankenpaxos_tpu.protocols import unanimousbpaxos_wire  # noqa: E402,F401
