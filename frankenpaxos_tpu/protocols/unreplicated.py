"""Unreplicated: a single server executing a state machine directly.

Reference behavior: unreplicated/ (unreplicated/Unreplicated.proto,
Server.scala, Client.scala). The throughput upper-bound baseline: no
consensus, just client -> server -> state machine -> reply, with
exactly-once via per-client command ids and client resend timers.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    client_address: Address
    client_pseudonym: int
    client_id: int
    command: bytes


@dataclasses.dataclass(frozen=True)
class ClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes


class UnreplicatedServer(Actor):
    """Executes commands in arrival order; caches the last reply per
    (client, pseudonym) for resend dedup."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, state_machine: StateMachine,
                 flush_every_n: int = 1):
        super().__init__(address, transport, logger)
        self.state_machine = state_machine
        self.flush_every_n = flush_every_n
        self._unflushed = 0
        # (client address, pseudonym) -> (largest executed id, its reply)
        self.client_table: dict[tuple, tuple[int, bytes]] = {}

    def receive(self, src: Address, message: ClientRequest) -> None:
        key = (message.client_address, message.client_pseudonym)
        executed = self.client_table.get(key)
        if executed is not None:
            largest_id, cached = executed
            if message.client_id < largest_id:
                return  # stale; client has moved on
            if message.client_id == largest_id:
                self.send(src, ClientReply(message.client_pseudonym,
                                           message.client_id, cached))
                return
        result = self.state_machine.run(message.command)
        self.client_table[key] = (message.client_id, result)
        reply = ClientReply(message.client_pseudonym, message.client_id,
                            result)
        if self.flush_every_n <= 1:
            self.send(src, reply)
        else:
            self.send_no_flush(src, reply)
            self._unflushed += 1
            if self._unflushed >= self.flush_every_n:
                self.flush(src)
                self._unflushed = 0


@dataclasses.dataclass
class _PendingCommand:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend_timer: object


class UnreplicatedClient(Actor):
    """Issues commands with per-pseudonym increasing ids; resends on
    timeout (unreplicated/Client.scala)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, server_address: Address,
                 resend_period_s: float = 1.0):
        super().__init__(address, transport, logger)
        self.server_address = server_address
        self.resend_period_s = resend_period_s
        self._ids: dict[int, int] = {}          # pseudonym -> next id
        self._pending: dict[int, _PendingCommand] = {}  # per pseudonym

    def propose(self, pseudonym: int, command: bytes,
                callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self._pending:
            raise RuntimeError(
                f"pseudonym {pseudonym} already has a pending command")
        client_id = self._ids.get(pseudonym, 0)
        request = ClientRequest(self.address, pseudonym, client_id, command)
        timer = self.timer(
            f"resend-{pseudonym}-{client_id}", self.resend_period_s,
            lambda: self._resend(pseudonym))
        timer.start()
        self._pending[pseudonym] = _PendingCommand(
            client_id, command, callback or (lambda _: None), timer)
        self.send(self.server_address, request)

    def _resend(self, pseudonym: int) -> None:
        pending = self._pending.get(pseudonym)
        if pending is None:
            return
        self.send(self.server_address,
                  ClientRequest(self.address, pseudonym, pending.id,
                                pending.command))
        pending.resend_timer.start()

    def receive(self, src: Address, message: ClientReply) -> None:
        pending = self._pending.get(message.client_pseudonym)
        if pending is None or pending.id != message.client_id:
            self.logger.debug(f"stale reply {message}")
            return
        pending.resend_timer.stop()
        del self._pending[message.client_pseudonym]
        self._ids[message.client_pseudonym] = message.client_id + 1
        pending.callback(message.result)


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
