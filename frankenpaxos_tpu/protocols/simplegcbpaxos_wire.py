"""Binary codecs for the SimpleGcBPaxos snapshot cold path (COD301
burn-down: the last two pickled protocol messages).

``SnapshotRequest`` is a field-less poke; ``CommitSnapshot`` is the
whole-snapshot transfer a recovering replica receives when the vertex
it asked for was already garbage collected. Both ride the wire only on
the recovery/GC path, but that is exactly the window where a cluster
must also survive ``set_pickle_fallback(False)``, so they get
fixed-layout codecs like BPaxosRecover (tag 200) before them.

Wire forms reuse the neighbours' layouts verbatim: the snapshot
watermark is a ``VertexIdPrefixSet`` dict (EPaxos column layout via
``_put_deps``/``_take_deps``); the client table is the
``ClientTable.to_dict`` kv list with ``(Address, pseudonym)`` keys.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols.epaxos.wire import _put_deps, _take_deps
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.protocols.simplebpaxos.messages import VertexIdPrefixSet
from frankenpaxos_tpu.protocols.simplegcbpaxos import (
    CommitSnapshot,
    SnapshotRequest,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")


def _put_int_prefix_set(out: bytearray, d: dict) -> None:
    """IntPrefixSet wire dict: [i64 watermark][i32 n][n x i64]."""
    out += _I64.pack(d["watermark"])
    values = d["values"]
    out += _I32.pack(len(values))
    for value in values:
        out += _I64.pack(value)


def _take_int_prefix_set(buf: bytes, at: int):
    (watermark,) = _I64.unpack_from(buf, at)
    (n,) = _I32.unpack_from(buf, at + 8)
    at += 12
    values = []
    for _ in range(n):
        (v,) = _I64.unpack_from(buf, at)
        values.append(v)
        at += 8
    # ``to_dict`` emits sorted values and encode preserves order, so
    # the decoded dict is bit-for-bit the canonical wire form.
    return {"watermark": watermark, "values": values}, at


def _put_client_table(out: bytearray, d: dict) -> None:
    """ClientTable wire dict (clienttable.ClientTable.to_dict): a kv
    list keyed by ``(client Address, i64 pseudonym)``."""
    kv = d["kv"]
    out += _I32.pack(len(kv))
    for entry in kv:
        address, pseudonym = entry["client"]
        _put_address(out, address)
        out += _I64I64.pack(pseudonym, entry["largest_id"])
        _put_bytes(out, entry["largest_output"])
        _put_int_prefix_set(out, entry["executed_ids"])


def _take_client_table(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    kv = []
    for _ in range(n):
        address, at = _take_address(buf, at)
        pseudonym, largest_id = _I64I64.unpack_from(buf, at)
        largest_output, at = _take_bytes(buf, at + 16)
        executed_ids, at = _take_int_prefix_set(buf, at)
        kv.append({
            "client": (address, pseudonym),
            "largest_id": largest_id,
            "largest_output": largest_output,
            "executed_ids": executed_ids,
        })
    return {"kv": kv}, at


class SnapshotRequestCodec(MessageCodec):
    message_type = SnapshotRequest
    tag = 206

    def encode(self, out, message):
        pass

    def decode(self, buf, at):
        return SnapshotRequest(), at


class CommitSnapshotCodec(MessageCodec):
    """The watermark rides the EPaxos deps column layout: the message
    field is the ``to_dict`` wire form, so encode lifts it back into a
    VertexIdPrefixSet and decode lowers it again -- ``to_dict`` is
    canonical (sorted values), so the round trip is exact."""

    message_type = CommitSnapshot
    tag = 207

    def encode(self, out, message):
        out += _I64.pack(message.id)
        _put_deps(out, VertexIdPrefixSet.from_dict(message.watermark))
        _put_bytes(out, message.state_machine)
        _put_client_table(out, message.client_table)

    def decode(self, buf, at):
        (id,) = _I64.unpack_from(buf, at)
        watermark, at = _take_deps(buf, at + 8)
        state_machine, at = _take_bytes(buf, at)
        client_table, at = _take_client_table(buf, at)
        return CommitSnapshot(id=id, watermark=watermark.to_dict(),
                              state_machine=state_machine,
                              client_table=client_table), at


for _codec in (SnapshotRequestCodec(), CommitSnapshotCodec()):
    register_codec(_codec)
