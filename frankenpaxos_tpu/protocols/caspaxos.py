"""CASPaxos: replicated compare-and-set state without a log.

Reference behavior: caspaxos/ (Leader.scala:79-470, Acceptor.scala:76-210).
State is a grow-only set of ints; each client request carries a set that
is unioned into the replicated state. The leader serializes requests:
Phase1 reads the highest-vote-round state from f+1 acceptors, applies
the client's change, Phase2 writes the new state to f+1. Nacks move the
leader to a randomized WaitingToRecover backoff (dueling-leader
avoidance, Leader.scala:433-470).

Note: the reference picks the phase-1 value with ``minBy(_.voteRound)``
(Leader.scala:342) while its own comment calls for the *largest* vote
round; we implement the comment (standard CASPaxos), not the bug.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.roundsystem import RotatedClassicRoundRobin, RoundSystem
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class CasPaxosConfig:
    f: int
    leader_addresses: tuple
    acceptor_addresses: tuple

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    client_address: Address
    client_id: int
    int_set: frozenset[int]


@dataclasses.dataclass(frozen=True)
class ClientReply:
    client_id: int
    value: frozenset[int]


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    acceptor_index: int
    vote_round: int
    vote_value: Optional[frozenset[int]]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    round: int
    value: frozenset[int]


@dataclasses.dataclass(frozen=True)
class Phase2b:
    round: int
    acceptor_index: int


@dataclasses.dataclass(frozen=True)
class Nack:
    higher_round: int


class CasPaxosLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CasPaxosConfig,
                 resend_period_s: float = 1.0,
                 recover_min_period_s: float = 0.1,
                 recover_max_period_s: float = 1.0, seed: int = 0):
        # Defaults mirror the reference (caspaxos/Leader.scala:27-30:
        # resend 1s, nack sleep 100ms-1s); deployments tune them to
        # their network RTT.
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.leader_addresses).index(address)
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.recover_min_period_s = recover_min_period_s
        self.recover_max_period_s = recover_max_period_s
        self.round_system: RoundSystem = RotatedClassicRoundRobin(
            len(config.leader_addresses), 0)
        # state: ("idle", round) | ("phase1", ...) | ("phase2", ...)
        #        | ("waiting", ...)
        self.status = "idle"
        self.round = self.round_system.next_classic_round(self.index, -1)
        self.client_requests: list[ClientRequest] = []
        self.phase1bs: dict[int, Phase1b] = {}
        self.phase2bs: dict[int, Phase2b] = {}
        self.phase2_value: Optional[frozenset] = None
        self.phase2_served: list = []
        self._resend_timer = None
        self._recover_timer = None

    # --- helpers ----------------------------------------------------------
    def _stop_timers(self) -> None:
        if self._resend_timer is not None:
            self._resend_timer.stop()
            self._resend_timer = None
        if self._recover_timer is not None:
            self._recover_timer.stop()
            self._recover_timer = None

    def _make_resend_timer(self, message) -> None:
        def resend():
            for acceptor in self.config.acceptor_addresses:
                self.send(acceptor, message)
            timer.start()

        timer = self.timer("resend", self.resend_period_s, resend)
        timer.start()
        self._resend_timer = timer

    def _transition_to_phase1(self, round: int) -> None:
        self._stop_timers()
        self.status = "phase1"
        self.round = round
        self.phase1bs.clear()
        phase1a = Phase1a(round=round)
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, phase1a)
        self._make_resend_timer(phase1a)

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Nack):
            self._handle_nack(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        self.client_requests.append(request)
        if self.status == "idle":
            self._transition_to_phase1(self.round)

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if self.status != "phase1" or phase1b.round != self.round:
            return
        self.phase1bs[phase1b.acceptor_index] = phase1b
        if len(self.phase1bs) < self.config.quorum_size:
            return
        best = max(self.phase1bs.values(), key=lambda r: r.vote_round)
        previous = (frozenset() if best.vote_round == -1
                    else best.vote_value)
        # Serve EVERY queued update in this one consensus round: the
        # register's CAS function is set union, which is associative,
        # so previous ∪ delta_1 ∪ ... ∪ delta_k is exactly the state a
        # serial execution of the k updates would reach, and each
        # client's reply (the accepted state) contains its delta. Under
        # contention this turns k dueling-prone rounds into one.
        served = list(self.client_requests)
        new_value = frozenset(previous.union(
            *(request.int_set for request in served)))
        self._stop_timers()
        self.status = "phase2"
        self.phase2_value = new_value
        self.phase2_served = served
        self.phase2bs.clear()
        phase2a = Phase2a(round=self.round, value=new_value)
        for acceptor in self.config.acceptor_addresses:
            self.send(acceptor, phase2a)
        self._make_resend_timer(phase2a)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        if self.status != "phase2" or phase2b.round != self.round:
            return
        self.phase2bs[phase2b.acceptor_index] = phase2b
        if len(self.phase2bs) < self.config.quorum_size:
            return
        served = self.phase2_served
        self.phase2_served = []
        # Requests that arrived during phase 2 stay queued for the next
        # round; the served prefix is acked with the accepted state.
        del self.client_requests[:len(served)]
        for request in served:
            self.send(request.client_address,
                      ClientReply(client_id=request.client_id,
                                  value=self.phase2_value))
        self._stop_timers()
        self.round = self.round_system.next_classic_round(self.index,
                                                          self.round)
        if self.client_requests:
            self._transition_to_phase1(self.round)
        else:
            self.status = "idle"

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        if nack.higher_round <= self.round:
            return
        new_round = self.round_system.next_classic_round(self.index,
                                                         nack.higher_round)
        self._stop_timers()
        self.round = new_round
        if self.status == "idle":
            return
        # Back off to avoid dueling leaders (Leader.scala:433-470).
        self.status = "waiting"

        def recover():
            self._transition_to_phase1(self.round)

        timer = self.timer(
            "recover",
            self.rng.uniform(self.recover_min_period_s,
                             self.recover_max_period_s),
            recover)
        timer.start()
        self._recover_timer = timer


class CasPaxosAcceptor(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CasPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[frozenset] = None

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            if message.round < self.round:
                self.send(src, Nack(higher_round=self.round))
                return
            self.round = message.round
            self.send(src, Phase1b(round=self.round,
                                   acceptor_index=self.index,
                                   vote_round=self.vote_round,
                                   vote_value=self.vote_value))
        elif isinstance(message, Phase2a):
            if message.round < self.round:
                self.send(src, Nack(higher_round=self.round))
                return
            self.round = message.round
            self.vote_round = message.round
            self.vote_value = message.value
            self.send(src, Phase2b(round=self.round,
                                   acceptor_index=self.index))
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")


class CasPaxosClient(Actor):
    """Propose set-union deltas; exactly-once per client id."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: CasPaxosConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.next_id = 0
        self.pending: Optional[tuple[int, ClientRequest, Callable,
                                     object]] = None

    def propose(self, int_set: frozenset[int] | set[int],
                callback: Optional[Callable[[frozenset], None]] = None
                ) -> None:
        if self.pending is not None:
            raise RuntimeError("a proposal is already pending")
        request = ClientRequest(self.address, self.next_id,
                                frozenset(int_set))
        self.next_id += 1
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))]
        self.send(leader, request)

        def resend():
            target = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))]
            self.send(target, request)
            timer.start()

        timer = self.timer("resend", self.resend_period_s, resend)
        timer.start()
        self.pending = (request.client_id, request,
                        callback or (lambda _: None), timer)

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        if self.pending is None or self.pending[0] != message.client_id:
            return
        _, _, callback, timer = self.pending
        timer.stop()
        self.pending = None
        callback(message.value)


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
