"""Binary codecs for the FasterPaxos steady-state write path.

Per-command traffic only (ClientRequest -> Phase2a -> Phase2b ->
Phase3a/Chosen -> ClientReply, fasterpaxos/FasterPaxos.proto); the
round-change / delegate-discovery messages are per-failover and stay
pickled. Phase2b optionally carries a command
(ackNoopsWithCommands, Server.scala:1613-1625) behind a kind byte.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import fasterpaxos as m
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_QQQ = struct.Struct("<qqq")


def _put_command(out: bytearray, command: m.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 16)
    return m.Command(m.CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    if isinstance(value, m.Noop):
        out.append(0)
    else:
        out.append(1)
        _put_command(out, value)


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return m.NOOP, at
    return _take_command(buf, at)


class FPClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 53

    def encode(self, out, message):
        out += _I64.pack(message.round)
        _put_command(out, message.command)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        command, at = _take_command(buf, at + 8)
        return m.ClientRequest(round, command), at


class FPPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 54

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return m.Phase2a(slot=slot, round=round, value=value), at


class FPPhase2bCodec(MessageCodec):
    message_type = m.Phase2b
    tag = 55

    def encode(self, out, message):
        out += _QQQ.pack(message.server_index, message.slot,
                         message.round)
        if message.command is None:
            out.append(0)
        else:
            out.append(1)
            _put_command(out, message.command)

    def decode(self, buf, at):
        server, slot, round = _QQQ.unpack_from(buf, at)
        at += _QQQ.size
        kind = buf[at]
        at += 1
        command = None
        if kind == 1:
            command, at = _take_command(buf, at)
        return m.Phase2b(server_index=server, slot=slot, round=round,
                         command=command), at


class FPPhase3aCodec(MessageCodec):
    """The chosen-value broadcast -- the highest-fanout per-command
    message (every choose fans to the other 2f servers)."""

    message_type = m.Phase3a
    tag = 57

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return m.Phase3a(slot=slot, value=value), at


class FPClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 56

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(m.CommandId(address, pseudonym, id),
                             result), at


def _put_delegates(out: bytearray, delegates: tuple) -> None:
    out += _I32.pack(len(delegates))
    for index in delegates:
        out += _I32.pack(index)


def _take_delegates(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    if n < 0 or n > (len(buf) - at - 4) // 4:
        raise ValueError(f"hostile delegate count {n}")
    at += 4
    delegates = []
    for _ in range(n):
        (index,) = _I32.unpack_from(buf, at)
        if not 0 <= index < (1 << 20):
            # Validate VALUES at the trust boundary too: a negative
            # index would silently wrap server_addresses[i] and
            # misroute; a huge one would IndexError deep in the actor
            # loop instead of being dropped as a corrupt frame here.
            raise ValueError(f"hostile delegate index {index}")
        delegates.append(index)
        at += 4
    return tuple(delegates), at


class FPPhase2aAnyCodec(MessageCodec):
    """The delegation handoff (extended tag 192; paxsafe COD301
    burn-down): carried on every round change, i.e. exactly when a
    failover storm is also resending every queued client op."""

    message_type = m.Phase2aAny
    tag = 192

    def encode(self, out, message):
        out += _I64.pack(message.round)
        _put_delegates(out, message.delegates)
        out += _I64.pack(message.start_slot)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        delegates, at = _take_delegates(buf, at + 8)
        (start_slot,) = _I64.unpack_from(buf, at)
        return m.Phase2aAny(round=round, delegates=delegates,
                            start_slot=start_slot), at + 8


class FPPhase2aAnyAckCodec(MessageCodec):
    message_type = m.Phase2aAnyAck
    tag = 193

    def encode(self, out, message):
        out += _I32.pack(message.server_index)
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (server,) = _I32.unpack_from(buf, at)
        (round,) = _I64.unpack_from(buf, at + 4)
        return m.Phase2aAnyAck(server_index=server, round=round), at + 12


class FPRoundInfoCodec(MessageCodec):
    """Leader -> client delegate discovery (extended tag 194): the
    reply every redirected client gets during a failover."""

    message_type = m.RoundInfo
    tag = 194

    def encode(self, out, message):
        out += _I64.pack(message.round)
        _put_delegates(out, message.delegates)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        delegates, at = _take_delegates(buf, at + 8)
        return m.RoundInfo(round=round, delegates=delegates), at


for _codec in (FPClientRequestCodec(), FPPhase2aCodec(),
               FPPhase2bCodec(), FPPhase3aCodec(),
               FPClientReplyCodec(), FPPhase2aAnyCodec(),
               FPPhase2aAnyAckCodec(), FPRoundInfoCodec()):
    register_codec(_codec)
