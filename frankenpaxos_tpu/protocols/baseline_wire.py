"""Binary codecs for the seven formerly pickle-only protocols.

Covers EVERY message of echo, unreplicated, batchedunreplicated, paxos,
fastpaxos, caspaxos, and matchmakerpaxos (the reference schemas: each
protocol's ``.proto`` next to its package, ProtoSerializer.scala:3-11).
These are small protocols, so full coverage is cheap -- and the first
three are the throughput *ceilings* every benchmark comparison
normalizes against (eurosys fig1: batched unreplicated ~1.11M/s), so
they must not pay the pickle tax (libbench: binary codecs measured
~2.4x pickle roundtrips/s).

Layouts follow the house style (multipaxos/wire.py): little-endian
fixed-width ints, length-prefixed bytes, kind-byte tagged unions for
optionals. No code execution on decode.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import (
    batchedunreplicated as bu,
    caspaxos as cp,
    echo as ec,
    fastpaxos as fp,
    matchmakerpaxos as mp,
    paxos as px,
    unreplicated as ur,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")


def _put_str(out: bytearray, s: str) -> None:
    _put_bytes(out, s.encode())


def _take_str(buf: bytes, at: int):
    raw, at = _take_bytes(buf, at)
    return raw.decode(), at


def _put_int_set(out: bytearray, xs) -> None:
    out += _I32.pack(len(xs))
    for x in sorted(xs):
        out += _I64.pack(x)


def _take_int_set(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    xs = []
    for _ in range(n):
        (x,) = _I64.unpack_from(buf, at)
        xs.append(x)
        at += 8
    return frozenset(xs), at


# --- echo -------------------------------------------------------------------


class EchoRequestCodec(MessageCodec):
    message_type = ec.EchoRequest
    tag = 76

    def encode(self, out, message):
        _put_str(out, message.msg)

    def decode(self, buf, at):
        msg, at = _take_str(buf, at)
        return ec.EchoRequest(msg), at


class EchoReplyCodec(MessageCodec):
    message_type = ec.EchoReply
    tag = 77

    def encode(self, out, message):
        _put_str(out, message.msg)

    def decode(self, buf, at):
        msg, at = _take_str(buf, at)
        return ec.EchoReply(msg), at


# --- unreplicated -----------------------------------------------------------


class UnrClientRequestCodec(MessageCodec):
    message_type = ur.ClientRequest
    tag = 78

    def encode(self, out, message):
        _put_address(out, message.client_address)
        out += _I64I64.pack(message.client_pseudonym, message.client_id)
        _put_bytes(out, message.command)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        command, at = _take_bytes(buf, at + 16)
        return ur.ClientRequest(address, pseudonym, id, command), at


class UnrClientReplyCodec(MessageCodec):
    message_type = ur.ClientReply
    tag = 79

    def encode(self, out, message):
        out += _I64I64.pack(message.client_pseudonym, message.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return ur.ClientReply(pseudonym, id, result), at


# --- batchedunreplicated ----------------------------------------------------


def _bu_put_command(out: bytearray, command: bu.Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64.pack(cid.client_id)
    _put_bytes(out, command.command)


def _bu_take_command(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    (client_id,) = _I64.unpack_from(buf, at)
    payload, at = _take_bytes(buf, at + 8)
    return bu.Command(bu.CommandId(address, client_id), payload), at


def _bu_put_reply(out: bytearray, reply: bu.ClientReply) -> None:
    cid = reply.command_id
    _put_address(out, cid.client_address)
    out += _I64.pack(cid.client_id)
    _put_bytes(out, reply.result)


def _bu_take_reply(buf: bytes, at: int):
    address, at = _take_address(buf, at)
    (client_id,) = _I64.unpack_from(buf, at)
    result, at = _take_bytes(buf, at + 8)
    return bu.ClientReply(bu.CommandId(address, client_id), result), at


class BuClientRequestCodec(MessageCodec):
    message_type = bu.ClientRequest
    tag = 80

    def encode(self, out, message):
        _bu_put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _bu_take_command(buf, at)
        return bu.ClientRequest(command), at


class BuClientRequestBatchCodec(MessageCodec):
    message_type = bu.ClientRequestBatch
    tag = 81

    def encode(self, out, message):
        out += _I32.pack(len(message.batch))
        for command in message.batch:
            _bu_put_command(out, command)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        batch = []
        for _ in range(n):
            command, at = _bu_take_command(buf, at)
            batch.append(command)
        return bu.ClientRequestBatch(tuple(batch)), at


class BuClientReplyCodec(MessageCodec):
    message_type = bu.ClientReply
    tag = 82

    def encode(self, out, message):
        _bu_put_reply(out, message)

    def decode(self, buf, at):
        return _bu_take_reply(buf, at)


class BuClientReplyBatchCodec(MessageCodec):
    message_type = bu.ClientReplyBatch
    tag = 83

    def encode(self, out, message):
        out += _I32.pack(len(message.batch))
        for reply in message.batch:
            _bu_put_reply(out, reply)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        batch = []
        for _ in range(n):
            reply, at = _bu_take_reply(buf, at)
            batch.append(reply)
        return bu.ClientReplyBatch(tuple(batch)), at


# --- paxos / fastpaxos (same shapes, distinct classes) ----------------------


def _put_opt_str(out: bytearray, s) -> None:
    if s is None:
        out.append(0)
    else:
        out.append(1)
        _put_str(out, s)


def _take_opt_str(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return None, at
    return _take_str(buf, at)


def _single_decree_codecs(ns, base_tag: int, prefix: str) -> list:
    """Codec classes for one single-decree package (paxos / fastpaxos --
    identical message shapes, including fastpaxos's ``value=None`` "any"
    marker in Phase2a, which _put_opt_str covers)."""

    class ProposeRequestCodec(MessageCodec):
        message_type = ns.ProposeRequest
        tag = base_tag

        def encode(self, out, message):
            _put_str(out, message.v)

        def decode(self, buf, at):
            v, at = _take_str(buf, at)
            return ns.ProposeRequest(v), at

    class ProposeReplyCodec(MessageCodec):
        message_type = ns.ProposeReply
        tag = base_tag + 1

        def encode(self, out, message):
            _put_str(out, message.chosen)

        def decode(self, buf, at):
            chosen, at = _take_str(buf, at)
            return ns.ProposeReply(chosen), at

    class Phase1aCodec(MessageCodec):
        message_type = ns.Phase1a
        tag = base_tag + 2

        def encode(self, out, message):
            out += _I64.pack(message.round)

        def decode(self, buf, at):
            (round,) = _I64.unpack_from(buf, at)
            return ns.Phase1a(round), at + 8

    class Phase1bCodec(MessageCodec):
        message_type = ns.Phase1b
        tag = base_tag + 3

        def encode(self, out, message):
            out += _I64.pack(message.round)
            out += _I64I64.pack(message.acceptor_id, message.vote_round)
            _put_opt_str(out, message.vote_value)

        def decode(self, buf, at):
            (round,) = _I64.unpack_from(buf, at)
            acceptor_id, vote_round = _I64I64.unpack_from(buf, at + 8)
            vote_value, at = _take_opt_str(buf, at + 24)
            return ns.Phase1b(round, acceptor_id, vote_round, vote_value), at

    class Phase2aCodec(MessageCodec):
        message_type = ns.Phase2a
        tag = base_tag + 4

        def encode(self, out, message):
            out += _I64.pack(message.round)
            _put_opt_str(out, message.value)

        def decode(self, buf, at):
            (round,) = _I64.unpack_from(buf, at)
            value, at = _take_opt_str(buf, at + 8)
            return ns.Phase2a(round, value), at

    class Phase2bCodec(MessageCodec):
        message_type = ns.Phase2b
        tag = base_tag + 5

        def encode(self, out, message):
            out += _I64I64.pack(message.acceptor_id, message.round)

        def decode(self, buf, at):
            acceptor_id, round = _I64I64.unpack_from(buf, at)
            return ns.Phase2b(acceptor_id, round), at + 16

    codecs = [ProposeRequestCodec, ProposeReplyCodec, Phase1aCodec,
              Phase1bCodec, Phase2aCodec, Phase2bCodec]
    for codec in codecs:
        codec.__name__ = prefix + codec.__name__
        codec.__qualname__ = codec.__name__
    return codecs


_PAXOS_CODECS = _single_decree_codecs(px, 84, "Paxos")
_FASTPAXOS_CODECS = _single_decree_codecs(fp, 90, "FastPaxos")


# --- caspaxos ---------------------------------------------------------------


class CasClientRequestCodec(MessageCodec):
    message_type = cp.ClientRequest
    tag = 96

    def encode(self, out, message):
        _put_address(out, message.client_address)
        out += _I64.pack(message.client_id)
        _put_int_set(out, message.int_set)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        (client_id,) = _I64.unpack_from(buf, at)
        int_set, at = _take_int_set(buf, at + 8)
        return cp.ClientRequest(address, client_id, int_set), at


class CasClientReplyCodec(MessageCodec):
    message_type = cp.ClientReply
    tag = 97

    def encode(self, out, message):
        out += _I64.pack(message.client_id)
        _put_int_set(out, message.value)

    def decode(self, buf, at):
        (client_id,) = _I64.unpack_from(buf, at)
        value, at = _take_int_set(buf, at + 8)
        return cp.ClientReply(client_id, value), at


class CasPhase1aCodec(MessageCodec):
    message_type = cp.Phase1a
    tag = 98

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return cp.Phase1a(round), at + 8


class CasPhase1bCodec(MessageCodec):
    message_type = cp.Phase1b
    tag = 99

    def encode(self, out, message):
        out += _I64.pack(message.round)
        out += _I64I64.pack(message.acceptor_index, message.vote_round)
        if message.vote_value is None:
            out.append(0)
        else:
            out.append(1)
            _put_int_set(out, message.vote_value)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        acceptor_index, vote_round = _I64I64.unpack_from(buf, at + 8)
        at += 24
        kind = buf[at]
        at += 1
        vote_value = None
        if kind == 1:
            vote_value, at = _take_int_set(buf, at)
        return cp.Phase1b(round, acceptor_index, vote_round, vote_value), at


class CasPhase2aCodec(MessageCodec):
    message_type = cp.Phase2a
    tag = 100

    def encode(self, out, message):
        out += _I64.pack(message.round)
        _put_int_set(out, message.value)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        value, at = _take_int_set(buf, at + 8)
        return cp.Phase2a(round, value), at


class CasPhase2bCodec(MessageCodec):
    message_type = cp.Phase2b
    tag = 101

    def encode(self, out, message):
        out += _I64I64.pack(message.round, message.acceptor_index)

    def decode(self, buf, at):
        round, acceptor_index = _I64I64.unpack_from(buf, at)
        return cp.Phase2b(round, acceptor_index), at + 16


class CasNackCodec(MessageCodec):
    message_type = cp.Nack
    tag = 102

    def encode(self, out, message):
        out += _I64.pack(message.higher_round)

    def decode(self, buf, at):
        (higher_round,) = _I64.unpack_from(buf, at)
        return cp.Nack(higher_round), at + 8


# --- matchmakerpaxos --------------------------------------------------------

_QS_KINDS = ("simple_majority", "unanimous_writes", "grid")


def _put_int_list(out: bytearray, xs) -> None:
    """Order-preserving (unlike _put_int_set): the wire dict's member
    and grid-row lists must round-trip exactly for message equality."""
    out += _I32.pack(len(xs))
    for x in xs:
        out += _I64.pack(x)


def _take_int_list(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    xs = []
    for _ in range(n):
        (x,) = _I64.unpack_from(buf, at)
        xs.append(x)
        at += 8
    return xs, at


def _put_quorum_system_dict(out: bytearray, d: dict) -> None:
    """The QuorumSystemProto analog (QuorumSystem.scala:26-44) in binary:
    kind byte + member list, or kind byte + row-major grid."""
    kind = d["kind"]
    out.append(_QS_KINDS.index(kind))
    if kind == "grid":
        out += _I32.pack(len(d["grid"]))
        for row in d["grid"]:
            _put_int_list(out, row)
    else:
        _put_int_list(out, d["members"])


def _take_quorum_system_dict(buf: bytes, at: int):
    kind = _QS_KINDS[buf[at]]
    at += 1
    if kind == "grid":
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        grid = []
        for _ in range(n):
            row, at = _take_int_list(buf, at)
            grid.append(row)
        return {"kind": kind, "grid": grid}, at
    members, at = _take_int_list(buf, at)
    return {"kind": kind, "members": members}, at


def _put_acceptor_group(out: bytearray, group: mp.AcceptorGroup) -> None:
    out += _I64.pack(group.round)
    _put_quorum_system_dict(out, group.quorum_system)


def _take_acceptor_group(buf: bytes, at: int):
    (round,) = _I64.unpack_from(buf, at)
    qs, at = _take_quorum_system_dict(buf, at + 8)
    return mp.AcceptorGroup(round, qs), at


class MpxClientRequestCodec(MessageCodec):
    message_type = mp.ClientRequest
    tag = 103

    def encode(self, out, message):
        _put_str(out, message.v)

    def decode(self, buf, at):
        v, at = _take_str(buf, at)
        return mp.ClientRequest(v), at


class MpxClientReplyCodec(MessageCodec):
    message_type = mp.ClientReply
    tag = 104

    def encode(self, out, message):
        _put_str(out, message.chosen)

    def decode(self, buf, at):
        chosen, at = _take_str(buf, at)
        return mp.ClientReply(chosen), at


class MpxMatchRequestCodec(MessageCodec):
    message_type = mp.MatchRequest
    tag = 105

    def encode(self, out, message):
        _put_acceptor_group(out, message.acceptor_group)

    def decode(self, buf, at):
        group, at = _take_acceptor_group(buf, at)
        return mp.MatchRequest(group), at


class MpxMatchReplyCodec(MessageCodec):
    message_type = mp.MatchReply
    tag = 106

    def encode(self, out, message):
        out += _I64I64.pack(message.round, message.matchmaker_index)
        out += _I32.pack(len(message.acceptor_groups))
        for group in message.acceptor_groups:
            _put_acceptor_group(out, group)

    def decode(self, buf, at):
        round, matchmaker_index = _I64I64.unpack_from(buf, at)
        (n,) = _I32.unpack_from(buf, at + 16)
        at += 20
        groups = []
        for _ in range(n):
            group, at = _take_acceptor_group(buf, at)
            groups.append(group)
        return mp.MatchReply(round, matchmaker_index, tuple(groups)), at


class MpxPhase1aCodec(MessageCodec):
    message_type = mp.Phase1a
    tag = 107

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return mp.Phase1a(round), at + 8


class MpxPhase1bCodec(MessageCodec):
    message_type = mp.Phase1b
    tag = 108

    def encode(self, out, message):
        out += _I64I64.pack(message.round, message.acceptor_index)
        if message.vote is None:
            out.append(0)
        else:
            out.append(1)
            out += _I64.pack(message.vote.vote_round)
            _put_str(out, message.vote.vote_value)

    def decode(self, buf, at):
        round, acceptor_index = _I64I64.unpack_from(buf, at)
        at += 16
        kind = buf[at]
        at += 1
        vote = None
        if kind == 1:
            (vote_round,) = _I64.unpack_from(buf, at)
            vote_value, at = _take_str(buf, at + 8)
            vote = mp.Phase1bVote(vote_round, vote_value)
        return mp.Phase1b(round, acceptor_index, vote), at


class MpxPhase2aCodec(MessageCodec):
    message_type = mp.Phase2a
    tag = 109

    def encode(self, out, message):
        out += _I64.pack(message.round)
        _put_str(out, message.value)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        value, at = _take_str(buf, at + 8)
        return mp.Phase2a(round, value), at


class MpxPhase2bCodec(MessageCodec):
    message_type = mp.Phase2b
    tag = 110

    def encode(self, out, message):
        out += _I64I64.pack(message.round, message.acceptor_index)

    def decode(self, buf, at):
        round, acceptor_index = _I64I64.unpack_from(buf, at)
        return mp.Phase2b(round, acceptor_index), at + 16


class MpxMatchmakerNackCodec(MessageCodec):
    message_type = mp.MatchmakerNack
    tag = 111

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return mp.MatchmakerNack(round), at + 8


class MpxAcceptorNackCodec(MessageCodec):
    message_type = mp.AcceptorNack
    tag = 112

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return mp.AcceptorNack(round), at + 8


for _codec_cls in (
    [EchoRequestCodec, EchoReplyCodec,
     UnrClientRequestCodec, UnrClientReplyCodec,
     BuClientRequestCodec, BuClientRequestBatchCodec,
     BuClientReplyCodec, BuClientReplyBatchCodec]
    + _PAXOS_CODECS + _FASTPAXOS_CODECS
    + [CasClientRequestCodec, CasClientReplyCodec, CasPhase1aCodec,
       CasPhase1bCodec, CasPhase2aCodec, CasPhase2bCodec, CasNackCodec,
       MpxClientRequestCodec, MpxClientReplyCodec, MpxMatchRequestCodec,
       MpxMatchReplyCodec, MpxPhase1aCodec, MpxPhase1bCodec,
       MpxPhase2aCodec, MpxPhase2bCodec, MpxMatchmakerNackCodec,
       MpxAcceptorNackCodec]
):
    register_codec(_codec_cls())
