"""Horizontal MultiPaxos: reconfigurable log chunks.

Reference behavior: horizontal/ (Leader.scala:38-1110, Acceptor.scala:
31-240, Replica.scala:34-420, Config.scala). The log is split into
*chunks*, each owned by its own quorum system over the acceptor pool. A
``Reconfigure(quorum_system)`` request is chosen INTO the log as a
Configuration value at slot s; once executed, a new chunk with the new
quorum system becomes active at slot ``s + alpha`` (the horizontal
reconfiguration rule: alpha bounds how far ahead proposals may run).
The active leader keeps one Phase1/Phase2 state per chunk; acceptors
key their state by (chunk first_slot, slot); replicas execute the
chosen log in order, skipping Configuration values.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

from frankenpaxos_tpu.election.basic import (
    ElectionOptions,
    ElectionParticipant,
)
from frankenpaxos_tpu.quorums import (
    quorum_system_from_dict,
    quorum_system_to_dict,
    QuorumSystem,
    SimpleMajority,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap


@dataclasses.dataclass(frozen=True)
class HorizontalConfig:
    f: int
    leader_addresses: tuple
    leader_election_addresses: tuple
    acceptor_addresses: tuple
    replica_addresses: tuple
    alpha: int = 3

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.leader_election_addresses) \
                != len(self.leader_addresses):
            raise ValueError("elections must mirror leaders")
        if len(self.acceptor_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


@dataclasses.dataclass(frozen=True)
class Configuration:
    quorum_system: dict  # wire form


NOOP = Noop()
Value = Union[Command, Noop, Configuration]


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class Reconfigure:
    quorum_system: dict


@dataclasses.dataclass(frozen=True)
class Die:
    """Chaos: the receiving leader stops processing messages
    (LeaderInbound.withDie, used by the driver's failure schedules)."""


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    first_slot: int
    chosen_watermark: int


@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    vote_round: int
    vote_value: Value


@dataclasses.dataclass(frozen=True)
class Phase1b:
    round: int
    first_slot: int
    acceptor_index: int
    info: tuple[Phase1bSlotInfo, ...]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    first_slot: int
    value: Value


@dataclasses.dataclass(frozen=True)
class Phase2b:
    slot: int
    round: int
    acceptor_index: int


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: Value


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    result: bytes


@dataclasses.dataclass(frozen=True)
class Nack:
    round: int


@dataclasses.dataclass
class _Chunk:
    first_slot: int
    last_slot: Optional[int]
    quorum_system: QuorumSystem
    # phase: ("phase1", {acceptor: Phase1b}) or
    #        ("phase2", next_slot, {slot: value}, {slot: set of voters})
    phase: list


class HorizontalLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: HorizontalConfig,
                 election_options: ElectionOptions = ElectionOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.index = list(config.leader_addresses).index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.log: BufferMap = BufferMap()
        self.chosen_watermark = 0
        self.round = 0
        self.active = False
        self.chunks: list[_Chunk] = []

        self.election = ElectionParticipant(
            config.leader_election_addresses[self.index], transport, logger,
            config.leader_election_addresses, initial_leader_index=0,
            options=election_options, seed=seed)
        self.election.register(self._on_leader_change)

        if self.index == 0:
            # Round 0: the initial chunk covers slot 0.. with a simple
            # majority over the first 2f+1 acceptors; phase 1 is skippable
            # in round 0 (nothing was ever proposed).
            quorum_system = SimpleMajority(range(2 * config.f + 1))
            self.active = True
            self.chunks = [_Chunk(0, None, quorum_system,
                                  ["phase2", 0, {}, {}])]

    # --- helpers ----------------------------------------------------------
    def _on_leader_change(self, leader_index: int) -> None:
        if getattr(self, "dead", False):
            return  # a killed leader must not be re-activated
        if leader_index == self.index:
            self._become_leader(
                self.round_system.next_classic_round(self.index, self.round))
        else:
            self.active = False
            self.chunks = []

    def _become_leader(self, round: int) -> None:
        self.round = round
        self.active = True
        # One chunk per active configuration; conservatively restart with
        # the last known chunk boundaries (fresh leaders re-learn via
        # phase 1 from the chosen watermark).
        if not self.chunks:
            quorum_system = SimpleMajority(range(2 * self.config.f + 1))
            self.chunks = [_Chunk(self.chosen_watermark, None,
                                  quorum_system, ["phase1", {}])]
        else:
            for chunk in self.chunks:
                chunk.phase = ["phase1", {}]
        for chunk in self.chunks:
            self._send_phase1as(chunk)

    def _send_phase1as(self, chunk: _Chunk) -> None:
        phase1a = Phase1a(round=self.round, first_slot=chunk.first_slot,
                          chosen_watermark=self.chosen_watermark)
        for i in chunk.quorum_system.nodes():
            self.send(self.config.acceptor_addresses[i], phase1a)

    def _chunk_of(self, slot: int) -> Optional[_Chunk]:
        for chunk in reversed(self.chunks):
            if slot >= chunk.first_slot:
                return chunk
        return None

    def _active_chunk(self) -> _Chunk:
        return self.chunks[-1] if self.chunks else None

    def _propose(self, chunk: _Chunk, value: Value) -> None:
        assert chunk.phase[0] == "phase2"
        slot = chunk.phase[1]
        chunk.phase[1] = slot + 1
        chunk.phase[2][slot] = value
        chunk.phase[3][slot] = set()
        phase2a = Phase2a(slot=slot, round=self.round,
                          first_slot=chunk.first_slot, value=value)
        for i in chunk.quorum_system.nodes():
            self.send(self.config.acceptor_addresses[i], phase2a)

    def _choose(self, slot: int, value: Value) -> None:
        already = self.log.get(slot) is not None
        self.log.put(slot, value)
        for replica in self.config.replica_addresses:
            self.send(replica, Chosen(slot=slot, value=value))
        for leader in self.config.leader_addresses:
            if leader != self.address:
                self.send(leader, Chosen(slot=slot, value=value))
        if not already:
            self._advance_watermark()

    def _advance_watermark(self) -> None:
        while self.log.get(self.chosen_watermark) is not None:
            value = self.log.get(self.chosen_watermark)
            slot = self.chosen_watermark
            self.chosen_watermark += 1
            if isinstance(value, Configuration) and self.active:
                # Activate a new chunk at slot + alpha
                # (Leader.scala:450-470 choose()).
                first_slot = slot + self.config.alpha
                current = self._active_chunk()
                if current is not None and current.first_slot < first_slot:
                    current.last_slot = first_slot - 1
                    # Fill this chunk's unproposed slots with noops so the
                    # log up to the boundary completes.
                    if current.phase[0] == "phase2":
                        while current.phase[1] < first_slot:
                            self._propose(current, NOOP)
                quorum_system = quorum_system_from_dict(value.quorum_system)
                chunk = _Chunk(first_slot, None, quorum_system,
                               ["phase2", first_slot, {}, {}])
                self.chunks.append(chunk)

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if getattr(self, "dead", False):
            return
        if isinstance(message, Die):
            self.dead = True
        elif isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, Reconfigure):
            self._handle_reconfigure(src, message)
        elif isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Chosen):
            if self.log.get(message.slot) is None:
                self.log.put(message.slot, message.value)
                self._advance_watermark()
        elif isinstance(message, Nack):
            self._handle_nack(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _alpha_overflow(self, chunk: _Chunk) -> bool:
        """At most alpha commands may be pending beyond the chosen
        watermark (horizontal/Leader.scala:638-646): a Configuration
        chosen at slot s governs slot s + alpha, so proposing past
        chosen_watermark + alpha could land in a chunk whose
        configuration is not yet known -- a later chunk activation
        would then re-propose the slot under a different quorum system
        and two values could be chosen for it (found by the 500x250
        soak, horizontal/f1 seed 475: replica logs diverged)."""
        return chunk.phase[1] >= self.chosen_watermark + self.config.alpha

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        if not self.active:
            return
        chunk = self._active_chunk()
        if chunk is None or chunk.phase[0] != "phase2":
            return  # phase 1 pending; client will resend
        if self._alpha_overflow(chunk):
            return  # dropped; the client resends (Leader.scala:643-646)
        self._propose(chunk, request.command)

    def _handle_reconfigure(self, src: Address,
                            reconfigure: Reconfigure) -> None:
        """Choose the new configuration as a log value
        (Leader.scala:1006-1018)."""
        if not self.active:
            return
        chunk = self._active_chunk()
        if chunk is None or chunk.phase[0] != "phase2":
            return
        if self._alpha_overflow(chunk):
            return  # dropped; the driver retries reconfigurations
        self._propose(chunk, Configuration(reconfigure.quorum_system))

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not self.active or phase1b.round != self.round:
            return
        chunk = next((c for c in self.chunks
                      if c.first_slot == phase1b.first_slot), None)
        if chunk is None or chunk.phase[0] != "phase1":
            return
        chunk.phase[1][phase1b.acceptor_index] = phase1b
        responders = set(chunk.phase[1])
        if not chunk.quorum_system.is_superset_of_read_quorum(responders):
            return
        # Adopt highest votes; fill holes with noops up to max voted slot.
        phase1bs = chunk.phase[1]
        max_slot = max((i.slot for p in phase1bs.values() for i in p.info),
                      default=chunk.first_slot - 1)
        chunk.phase = ["phase2", max(chunk.first_slot,
                                     self.chosen_watermark), {}, {}]
        for slot in range(chunk.first_slot, max_slot + 1):
            if self.log.get(slot) is not None:
                continue
            infos = [i for p in phase1bs.values() for i in p.info
                     if i.slot == slot]
            value = (max(infos, key=lambda i: i.vote_round).vote_value
                     if infos else NOOP)
            if slot >= chunk.phase[1]:
                chunk.phase[1] = slot + 1
            chunk.phase[2][slot] = value
            chunk.phase[3][slot] = set()
            phase2a = Phase2a(slot=slot, round=self.round,
                              first_slot=chunk.first_slot, value=value)
            for i in chunk.quorum_system.nodes():
                self.send(self.config.acceptor_addresses[i], phase2a)

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        if not self.active or phase2b.round != self.round:
            return
        chunk = self._chunk_of(phase2b.slot)
        if chunk is None or chunk.phase[0] != "phase2":
            return
        voters = chunk.phase[3].get(phase2b.slot)
        if voters is None:
            return
        voters.add(phase2b.acceptor_index)
        if not chunk.quorum_system.is_superset_of_write_quorum(voters):
            return
        value = chunk.phase[2].pop(phase2b.slot)
        del chunk.phase[3][phase2b.slot]
        self._choose(phase2b.slot, value)

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        if nack.round <= self.round:
            return
        if self.active:
            self._become_leader(
                self.round_system.next_classic_round(self.index,
                                                     nack.round))
        else:
            self.round = nack.round


@dataclasses.dataclass
class _AcceptorState:
    round: int = -1
    vote_round: int = -1
    vote_value: Optional[Value] = None


class HorizontalAcceptor(Actor):
    """Per-chunk rounds: state keyed by (first_slot) for rounds and slot
    for votes (Acceptor.scala:31-240)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: HorizontalConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.chunk_rounds: dict[int, int] = {}
        self.votes: dict[int, _AcceptorState] = {}

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            round = self.chunk_rounds.get(message.first_slot, -1)
            if message.round < round:
                self.send(src, Nack(round=round))
                return
            self.chunk_rounds[message.first_slot] = message.round
            info = tuple(
                Phase1bSlotInfo(slot=slot, vote_round=state.vote_round,
                                vote_value=state.vote_value)
                for slot, state in sorted(self.votes.items())
                if slot >= max(message.first_slot,
                               message.chosen_watermark)
                and state.vote_value is not None)
            self.send(src, Phase1b(round=message.round,
                                   first_slot=message.first_slot,
                                   acceptor_index=self.index, info=info))
        elif isinstance(message, Phase2a):
            round = self.chunk_rounds.get(message.first_slot, -1)
            if message.round < round:
                self.send(src, Nack(round=round))
                return
            self.chunk_rounds[message.first_slot] = message.round
            state = self.votes.setdefault(message.slot, _AcceptorState())
            state.round = message.round
            state.vote_round = message.round
            state.vote_value = message.value
            self.send(src, Phase2b(slot=message.slot, round=message.round,
                                   acceptor_index=self.index))
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")


class HorizontalReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: HorizontalConfig,
                 state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.index = list(config.replica_addresses).index(address)
        self.log: BufferMap = BufferMap()
        self.executed_watermark = 0
        self.client_table: dict[tuple, tuple[int, bytes]] = {}

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, Chosen):
            self.logger.fatal(f"unexpected replica message {message!r}")
        if self.log.get(message.slot) is None:
            self.log.put(message.slot, message.value)
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return
            slot = self.executed_watermark
            self.executed_watermark += 1
            if isinstance(value, (Noop, Configuration)):
                continue
            cid = value.command_id
            key = (cid.client_address, cid.client_pseudonym)
            cached = self.client_table.get(key)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(value.command)
                self.client_table[key] = (cid.client_id, result)
            if slot % len(self.config.replica_addresses) == self.index:
                self.send(cid.client_address,
                          ClientReply(command_id=cid, result=result))


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class HorizontalClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: HorizontalConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, id), command))

        def send_it():
            for leader in self.config.leader_addresses:
                self.send(leader, request)

        def resend():
            send_it()
            timer.start()

        send_it()
        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def reconfigure(self, quorum_system: QuorumSystem) -> None:
        for leader in self.config.leader_addresses:
            self.send(leader,
                      Reconfigure(quorum_system_to_dict(quorum_system)))

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.command_id.client_pseudonym)
        if pending is None or pending.id != message.command_id.client_id:
            return
        pending.resend.stop()
        del self.pending[message.command_id.client_pseudonym]
        pending.callback(message.result)


# --- driver-based chaos workloads ------------------------------------------
# (jvm/.../horizontal/Driver.scala + DriverWorkload.proto: scripted
# schedules of reconfigurations, forced leader changes, and leader
# failures, used for the chunk-reconfiguration experiments.)


@dataclasses.dataclass(frozen=True)
class DoNothing:
    pass


@dataclasses.dataclass(frozen=True)
class RepeatedLeaderReconfiguration:
    """Every ``period_s`` (after ``delay_s``), leader 0 reconfigures to
    a 2f+1 acceptor subset (DriverWorkload.proto:12-17)."""

    acceptors: tuple
    delay_s: float
    period_s: float


@dataclasses.dataclass(frozen=True)
class LeaderReconfiguration:
    """Warmup reconfigurations, then measured ones, then an acceptor
    failure + recovery (DriverWorkload.proto:19-29)."""

    reconfiguration_warmup_delay_s: float
    reconfiguration_warmup_period_s: float
    reconfiguration_warmup_num: int
    reconfiguration_delay_s: float
    reconfiguration_period_s: float
    reconfiguration_num: int
    failure_delay_s: float
    recover_delay_s: float


@dataclasses.dataclass(frozen=True)
class LeaderFailure:
    """Forced leader-change warmups, then kill leader 0
    (DriverWorkload.proto:31-36)."""

    leader_change_warmup_delay_s: float
    leader_change_warmup_period_s: float
    leader_change_warmup_num: int
    failure_delay_s: float


DriverWorkload = Union[DoNothing, RepeatedLeaderReconfiguration,
                       LeaderReconfiguration, LeaderFailure]


class HorizontalDriver(Actor):
    """Executes a scripted chaos schedule against the deployment
    (Driver.scala:30-312): reconfigure via Reconfigure to leader 0,
    force leader changes via ForceNoPing to election participants, kill
    leaders via Die."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: HorizontalConfig,
                 workload: DriverWorkload, seed: int = 0):
        super().__init__(address, transport, logger)
        self.config = config
        self.workload = workload
        self.rng = random.Random(seed)
        self.timers: list = []
        self._start()

    # --- actions (Driver.scala:130-150) -----------------------------------
    def reconfigure(self, acceptors=None) -> None:
        if acceptors is None:
            n = len(self.config.acceptor_addresses)
            acceptors = self.rng.sample(range(n), 2 * self.config.f + 1)
        self.send(self.config.leader_addresses[0], Reconfigure(
            quorum_system_to_dict(SimpleMajority(acceptors))))

    def become_leader(self, index: int) -> None:
        from frankenpaxos_tpu.election.basic import ForceNoPing

        self.send(self.config.leader_election_addresses[index],
                  ForceNoPing())

    def kill_leader(self, index: int) -> None:
        self.send(self.config.leader_addresses[index], Die())

    # --- schedule wiring (Driver.scala:98-129) -----------------------------
    def _delayed_repeating(self, name: str, delay_s: float,
                           period_s: float, n: int, fire,
                           on_last=None) -> None:
        from frankenpaxos_tpu.protocols.driver_util import delayed_repeating

        self.timers += delayed_repeating(self, name, delay_s, period_s, n,
                                         fire, on_last)

    def _start(self) -> None:
        w = self.workload
        if isinstance(w, DoNothing):
            return
        if isinstance(w, RepeatedLeaderReconfiguration):
            from frankenpaxos_tpu.protocols.driver_util import repeating

            self.timers += repeating(
                self, "reconfigure", w.delay_s, w.period_s,
                lambda: self.send(
                    self.config.leader_addresses[0],
                    Reconfigure(quorum_system_to_dict(
                        SimpleMajority(w.acceptors)))))
            return
        if isinstance(w, LeaderReconfiguration):
            self._delayed_repeating(
                "warmupReconfigure", w.reconfiguration_warmup_delay_s,
                w.reconfiguration_warmup_period_s,
                w.reconfiguration_warmup_num,
                self.reconfigure, self.reconfigure)
            self._delayed_repeating(
                "reconfigure", w.reconfiguration_delay_s,
                w.reconfiguration_period_s, w.reconfiguration_num,
                self.reconfigure, self.reconfigure)
            # Failure + recovery: drop to a bare quorum that excludes
            # acceptor 0 (possible only when spare acceptors exist),
            # then return to the initial set.
            n = len(self.config.acceptor_addresses)
            quorum = 2 * self.config.f + 1

            def fail():
                if n > quorum:
                    self.reconfigure(list(range(1, quorum + 1)))
                else:
                    self.logger.warn(
                        "no spare acceptors; failure step skipped")

            def recover():
                self.reconfigure(list(range(quorum)))

            t_fail = self.timer("failure", w.failure_delay_s, fail)
            t_recover = self.timer("recover", w.recover_delay_s, recover)
            t_fail.start()
            t_recover.start()
            self.timers += [t_fail, t_recover]
            return
        if isinstance(w, LeaderFailure):
            self._delayed_repeating(
                "leaderChangeWarmup", w.leader_change_warmup_delay_s,
                w.leader_change_warmup_period_s,
                w.leader_change_warmup_num,
                lambda: self.become_leader(1),
                lambda: self.become_leader(0))
            t_fail = self.timer("failure", w.failure_delay_s, lambda: (
                self.kill_leader(0), self.become_leader(1)))
            t_fail.start()
            self.timers.append(t_fail)
            return
        self.logger.fatal(f"unknown driver workload {w!r}")

    def receive(self, src: Address, message) -> None:
        self.logger.fatal(f"driver got unexpected message {message!r}")

# Importing registers the Horizontal binary codecs with the hybrid
# serializer (see horizontal_wire.py).
from frankenpaxos_tpu.protocols import horizontal_wire  # noqa: E402,F401
