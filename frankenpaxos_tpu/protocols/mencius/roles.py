"""Mencius Batcher, Leader, ProxyLeader, and Acceptor.

Reference behavior: mencius/Batcher.scala:85-190, Leader.scala:130-870,
ProxyLeader.scala:31-420, Acceptor.scala:103-300.
"""

from __future__ import annotations

import dataclasses
import random

try:
    from sortedcontainers import SortedDict  # type: ignore[import-untyped]
except ImportError:  # stripped environments: pure-Python fallback
    from frankenpaxos_tpu.utils.sorted_compat import SortedDict

from frankenpaxos_tpu.election.basic import (
    ElectionOptions,
    ElectionParticipant,
)
from frankenpaxos_tpu.protocols.mencius.common import (
    Chosen,
    ChosenNoopRange,
    ChosenRun,
    ChosenWatermark,
    ClientRequest,
    ClientRequestArray,
    ClientRequestBatch,
    CommandBatch,
    DistributionScheme,
    HighWatermark,
    LeaderInfoReplyBatcher,
    LeaderInfoReplyClient,
    LeaderInfoRequestBatcher,
    LeaderInfoRequestClient,
    MenciusConfig,
    Nack,
    NOOP,
    NotLeaderBatcher,
    NotLeaderClient,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2aNoopRange,
    Phase2aRun,
    Phase2b,
    Phase2bNoopRange,
    Phase2bRun,
    Recover,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    decode_value,
    decode_value_array,
    encode_value,
    encode_value_array,
)
from frankenpaxos_tpu.reconfig import (
    decode_epoch_config,
    encode_epoch_config,
    EpochAck,
    EpochCommit,
    EpochConfig,
    EpochStore,
    Reconfigure,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.wal import (
    DurableRole,
    WalEpoch,
    WalNoopRange,
    WalPromise,
    WalSnapshot,
    WalVote,
    WalVoteRun,
)


class MenciusBatcher(Actor):
    """(Batcher.scala:85-190): batch, then send to the current round's
    leader of a random leader group (Hash) or the colocated group."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig,
                 batch_size: int = 1, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.batch_size = batch_size
        self.rng = random.Random(seed)
        self.index = (list(config.batcher_addresses).index(address)
                      if address in config.batcher_addresses else 0)
        # Known round per leader group.
        self.rounds = [0] * config.num_leader_groups
        self.growing_batch: list = []
        self.pending_resend_batches: list = []

    def _group_leader(self, group: int) -> Address:
        rs = ClassicRoundRobin(len(self.config.leader_addresses[group]))
        return self.config.leader_addresses[group][
            rs.leader(self.rounds[group])]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self.growing_batch.append(message.command)
            if len(self.growing_batch) >= self.batch_size:
                if (self.config.distribution_scheme
                        == DistributionScheme.HASH):
                    group = self.rng.randrange(
                        self.config.num_leader_groups)
                else:
                    group = self.index % self.config.num_leader_groups
                self.send(self._group_leader(group), ClientRequestBatch(
                    CommandBatch(tuple(self.growing_batch))))
                self.growing_batch.clear()
        elif isinstance(message, NotLeaderBatcher):
            self.pending_resend_batches.append(
                (message.leader_group_index, message.client_request_batch))
            for leader in self.config.leader_addresses[
                    message.leader_group_index]:
                self.send(leader, LeaderInfoRequestBatcher())
        elif isinstance(message, LeaderInfoReplyBatcher):
            if message.round > self.rounds[message.leader_group_index]:
                self.rounds[message.leader_group_index] = message.round
            still_pending = []
            for group, batch in self.pending_resend_batches:
                if group == message.leader_group_index:
                    self.send(self._group_leader(group), batch)
                else:
                    still_pending.append((group, batch))
            self.pending_resend_batches = still_pending
        else:
            self.logger.fatal(f"unexpected batcher message {message!r}")


@dataclasses.dataclass
class _Phase1:
    # One dict per acceptor group of this leader group.
    phase1bs: list[dict[int, Phase1b]]
    pending_batches: list[ClientRequestBatch]
    # Slot to force-recover through phase 1, or -1 (Leader.scala:160-172).
    recover_slot: int
    resend_phase1as: object
    # Address-keyed Phase1bs + the in-flight Phase1a (reconfig: across
    # epochs, (group, index) coordinates can collide; addresses cannot).
    by_addr: dict = dataclasses.field(default_factory=dict)
    phase1a: object = None


@dataclasses.dataclass
class _EpochChange:
    """A Mencius epoch change in flight. Unlike MultiPaxos (whose
    proposals carry epoch tags and stash at a lagging proxy), Mencius
    runs stay untagged, so activation additionally gates on EVERY
    proxy leader's ack -- a proxy can then never mis-route a new-epoch
    run to the old set. The trade-off: a dead proxy blocks
    reconfiguration here, where MultiPaxos rides through
    (docs/RECONFIG.md)."""

    config: EpochConfig
    commit: EpochCommit
    targets: set
    acks: set
    resend: object
    pending: list  # buffered ClientRequestBatch
    activated: bool = False
    # True when re-driving an adopted epoch (post-failover / peer
    # broadcast); targets and gating then depend on whether the
    # predecessor-quorum durability was already PROVEN by Phase1bs.
    recommit: bool = False
    # Activation must (re-)establish f+1 predecessor-epoch durable
    # acks unless Phase1 already proved them (chaos-found: proposing
    # into an adopted-but-undurable epoch lets a later leader that
    # misses it re-propose its slots under the old quorums -- a second
    # chosen value).
    need_old_quorum: bool = True


class MenciusLeader(Actor):
    """(mencius/Leader.scala:130-870)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig,
                 resend_phase1as_period_s: float = 5.0,
                 send_high_watermark_every_n: int = 100,
                 send_noop_range_if_lagging_by: int = 100,
                 election_options: ElectionOptions = ElectionOptions(),
                 seed: int = 0,
                 admission_token_rate: float = 0.0,
                 admission_token_burst: float = 0.0,
                 admission_inflight_limit: int = 0,
                 admission_inbox_capacity: int = 0,
                 admission_inbox_policy: str = "reject",
                 admission_codel_target_s: float = 0.0,
                 admission_codel_interval_s: float = 0.1,
                 admission_retry_after_ms: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        # paxload admission (serve/): built only when armed; the
        # in-flight measure is this group's owned-slot span
        # (next_slot - chosen_watermark) / stride, refreshed on
        # proposals and ChosenWatermark advances.
        from frankenpaxos_tpu.serve.admission import (
            AdmissionController,
            AdmissionOptions,
        )

        admission_options = AdmissionOptions(
            token_rate=admission_token_rate,
            token_burst=admission_token_burst,
            inflight_limit=admission_inflight_limit,
            inbox_capacity=admission_inbox_capacity,
            inbox_policy=admission_inbox_policy,
            codel_target_s=admission_codel_target_s,
            codel_interval_s=admission_codel_interval_s,
            retry_after_ms=admission_retry_after_ms)
        if admission_options.any_enabled():
            self.admission = AdmissionController(
                admission_options, role="mencius_leader",
                metrics=transport.runtime_metrics)
            transport.note_admission(address, self)
        self.rng = random.Random(seed)
        self.send_high_watermark_every_n = send_high_watermark_every_n
        self.send_noop_range_if_lagging_by = send_noop_range_if_lagging_by
        self.resend_phase1as_period_s = resend_phase1as_period_s
        self.group_index = next(
            g for g, group in enumerate(config.leader_addresses)
            if address in group)
        self.index = list(
            config.leader_addresses[self.group_index]).index(address)
        self.round_system = ClassicRoundRobin(
            len(config.leader_addresses[self.group_index]))
        # Which leader group owns which slot (Leader.scala:208-213).
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        self.round = self.round_system.next_classic_round(0, -1)
        self.next_slot = self.group_index
        self.high_watermark = self.next_slot
        self.chosen_watermark = 0
        # Commands admitted while in _Phase1 (pending_batches, no slot
        # yet) -- counted by the in-flight resyncs (see the multipaxos
        # leader's _sync_inflight).
        self._admitted_backlog = 0
        self._commands_since_watermark_send = 0
        self._current_proxy_leader = self.rng.randrange(
            config.num_proxy_leaders)
        # paxfan descriptor pipelining: per-batcher drained-seq
        # high-water, flushed as ONE IngestCredit per batcher per
        # drain (the multipaxos leader's twin).
        self._ingest_credit_hw: dict = {}

        self.election = ElectionParticipant(
            config.leader_election_addresses[self.group_index][self.index],
            transport, logger,
            config.leader_election_addresses[self.group_index],
            initial_leader_index=0, options=election_options, seed=seed)
        self.election.register(
            lambda leader_index: self.leader_change(
                leader_index == self.index, recover_slot=-1))

        # Live reconfiguration (reconfig/): one epoch store per leader
        # group, over ITS owned slots -- supported when the group has
        # exactly one 2f+1 acceptor group (the run-pipeline shape).
        self.epochs: object = None
        if len(config.acceptor_addresses[self.group_index]) == 1:
            self.epochs = EpochStore.from_members(
                tuple(config.acceptor_addresses[self.group_index][0]),
                config.f)
        self._epoch_change: object = None

        self.state: object = ("inactive",)
        if self.index == 0:
            self.state = self._start_phase1(self.round,
                                            self.chosen_watermark, -1)

    # --- helpers ----------------------------------------------------------
    # Multi-acceptor-group striping is epoch-frozen (reconfig swaps
    # members within the single group; see the PAX110 pragmas on the
    # striping helpers below).
    @property
    def _my_acceptor_groups(self) -> tuple:  # paxlint: disable=PAX110
        return self.config.acceptor_addresses[self.group_index]

    def _acceptor_group_index_by_slot(self, slot: int) -> int:
        self.logger.check_eq(self.slot_system.leader(slot), self.group_index)
        return ((slot // self.config.num_leader_groups)
                % len(self._my_acceptor_groups))

    def _proxy_leader(self) -> Address:
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_leader_addresses[
                self._current_proxy_leader]
        return self.config.proxy_leader_addresses[self.group_index]

    def _advance_proxy_leader(self) -> None:
        self._current_proxy_leader = (
            (self._current_proxy_leader + 1) % self.config.num_proxy_leaders)

    @staticmethod
    def _safe_value(phase1bs, slot: int):
        best_round, best_value = -1, None
        for phase1b in phase1bs:
            for info in phase1b.info:
                if info.slot == slot and info.vote_round > best_round:
                    best_round, best_value = info.vote_round, info.vote_value
        return NOOP if best_value is None else best_value

    def _phase1_epochs(self) -> list:
        return self.epochs.epochs_covering(self.chosen_watermark)

    def _start_phase1(self, round: int, chosen_watermark: int,
                      recover_slot: int) -> _Phase1:
        phase1a = Phase1a(round=round, chosen_watermark=chosen_watermark)
        if self.epochs is not None:
            # Per covered epoch, a thrifty read-quorum sample; resend
            # widens to every member (dict.fromkeys: deterministic
            # iteration under hash randomization).
            targets: dict = {}
            for config in self._phase1_epochs():
                targets.update(dict.fromkeys(self.rng.sample(
                    list(config.members), config.quorum_size)))
            for acceptor in targets:
                self.send(acceptor, phase1a)
        else:
            for group in self._my_acceptor_groups:
                for acceptor in self.rng.sample(list(group),
                                                self.config.quorum_size):
                    self.send(acceptor, phase1a)

        def resend():
            if self.epochs is not None:
                targets: dict = {}
                for config in self._phase1_epochs():
                    targets.update(dict.fromkeys(config.members))
                for acceptor in targets:
                    self.send(acceptor, phase1a)
            else:
                for group in self._my_acceptor_groups:
                    for acceptor in group:
                        self.send(acceptor, phase1a)
            timer.start()

        timer = self.timer("resendPhase1as", self.resend_phase1as_period_s,
                           resend)
        timer.start()
        # Fresh Phase1 = fresh (empty) pending backlog.
        self._admitted_backlog = 0
        return _Phase1(
            phase1bs=[{} for _ in self._my_acceptor_groups],
            pending_batches=[], recover_slot=recover_slot,
            resend_phase1as=timer, phase1a=phase1a)

    def _abort_epoch_change(self) -> None:
        change = self._epoch_change
        if change is None:
            return
        change.resend.stop()
        if change.pending:
            self.logger.debug(
                f"epoch change aborted with {len(change.pending)} "
                f"buffered batches (clients will resend)")
        self._epoch_change = None

    def leader_change(self, is_new_leader: bool, recover_slot: int) -> None:
        if isinstance(self.state, _Phase1):
            self.state.resend_phase1as.stop()
        self._abort_epoch_change()
        if not is_new_leader:
            self.state = ("inactive",)
            return
        self.round = self.round_system.next_classic_round(self.index,
                                                          self.round)
        self.state = self._start_phase1(self.round, self.chosen_watermark,
                                        recover_slot)

    def _process_batch(self, batch: ClientRequestBatch) -> None:
        self.logger.check_eq(self.state, ("phase2",))
        change = self._epoch_change
        if change is not None and not change.activated:
            change.pending.append(batch)
            return
        self.send(self._proxy_leader(),
                  Phase2a(slot=self.next_slot, round=self.round,
                          value=batch.batch))
        self._advance_proxy_leader()
        self.next_slot += self.config.num_leader_groups
        self._gossip_watermark(1)

    def _gossip_watermark(self, commands: int) -> None:
        # Periodically gossip our nextSlot so laggards can skip
        # (Leader.scala:455-480). A k-command run counts k commands.
        self._commands_since_watermark_send += commands
        if (self._commands_since_watermark_send
                >= self.send_high_watermark_every_n):
            self.send(self._proxy_leader(),
                      HighWatermark(next_slot=self.next_slot))
            self._commands_since_watermark_send = 0

    # --- paxingest (ingest/, docs/TRANSPORT.md) ---------------------------
    def _note_ingest(self, cmds: int, nbytes: int) -> None:
        metrics = self.transport.runtime_metrics
        if metrics is not None:
            metrics.ingest_batch(cmds, nbytes)

    def _propose_value_run(self, values) -> None:
        """Post-admission Phase2 proposal of one-value-per-OWNED-slot
        ``values`` (tuple or LazyValueArray forwarded raw): the shared
        tail of the array / wire-column / IngestRun paths."""
        self.logger.check_eq(self.state, ("phase2",))
        if len(self._my_acceptor_groups) > 1:
            # Strided runs need a single acceptor audience; per-slot
            # fallback (iterating decodes a lazy array -- this config
            # is off the zero-object path).
            for value in values:
                self._process_batch(ClientRequestBatch(value))
            return
        change = self._epoch_change
        if change is not None and not change.activated:
            change.pending.extend(
                ClientRequestBatch(value) for value in values)
            return
        stride = self.config.num_leader_groups
        k = len(values)
        self.send(self._proxy_leader(), Phase2aRun(
            start_slot=self.next_slot, stride=stride, round=self.round,
            values=values))
        self._advance_proxy_leader()
        self.next_slot += k * stride
        self._gossip_watermark(k)

    def _handle_ingest_run(self, src: Address, run) -> None:
        """A disseminator's pre-batched run descriptor: one strided
        Phase2aRun from pre-encoded values -- this leader touches only
        run metadata (see the multipaxos twin)."""
        from frankenpaxos_tpu.ingest.columns import (
            reject_value_suffix,
            value_view,
        )
        from frankenpaxos_tpu.ingest.messages import NotLeaderIngest

        values = run.values
        n = len(values)
        if n == 0:
            return
        if self.state == ("inactive",):
            self.send(src, NotLeaderIngest(group_index=self.group_index,
                                           run=run))
            return
        # Credit the batcher's pipelining window (see the multipaxos
        # twin): consumed on every non-bounce path below.
        hw = self._ingest_credit_hw.get(src)
        if hw is None or run.seq > hw:
            self._ingest_credit_hw[src] = run.seq
        k = n
        admission = self.admission
        if admission is not None:
            k = admission.admit_up_to(n)
            if k < n:
                reject_value_suffix(self.send, values, k, admission)
                if k == 0:
                    return
                view = value_view(values)
                values = (view.lazy_values(k) if view is not None
                          else tuple(values)[:k])
        if isinstance(self.state, _Phase1):
            self._admitted_backlog += k
            for value in tuple(values)[:k]:  # cold: Phase1 only
                self.state.pending_batches.append(
                    ClientRequestBatch(value))
            return
        self._note_ingest(k, len(getattr(values, "raw", b"")))
        self._propose_value_run(values)

    def on_drain(self) -> None:
        """Flush accumulated pipelining credits: ONE watermark-granular
        IngestCredit per batcher per drain. Control-lane, so shedding
        never wedges the batchers' windows."""
        if self._ingest_credit_hw:
            from frankenpaxos_tpu.ingest.messages import IngestCredit

            credits, self._ingest_credit_hw = self._ingest_credit_hw, {}
            for src, hw in credits.items():
                self.send(src, IngestCredit(
                    group_index=self.group_index, watermark_seq=hw))

    def _process_request_array(self, array: ClientRequestArray) -> None:
        """A drain's worth of independent requests: assign each its own
        OWNED slot (next_slot, next_slot + G, ...) and propose the whole
        strided block as ONE Phase2aRun carrying the stride.

        Slots within one leader group also stripe over its acceptor
        groups ((slot // G) % num_acceptor_groups), so a strided run has
        a single acceptor audience only with one acceptor group; with
        more, fall back to per-slot proposals."""
        self.logger.check_eq(self.state, ("phase2",))
        if len(self._my_acceptor_groups) > 1:
            for command in array.commands:
                self._process_batch(
                    ClientRequestBatch(CommandBatch((command,))))
            return
        change = self._epoch_change
        if change is not None and not change.activated:
            # Handover window: buffer until the commit's activation
            # quorum (old-epoch write quorum + every proxy) is in.
            change.pending.extend(
                ClientRequestBatch(CommandBatch((c,)))
                for c in array.commands)
            return
        stride = self.config.num_leader_groups
        k = len(array.commands)
        self.send(self._proxy_leader(), Phase2aRun(
            start_slot=self.next_slot, stride=stride, round=self.round,
            values=tuple(CommandBatch((c,)) for c in array.commands)))
        self._advance_proxy_leader()
        self.next_slot += k * stride
        self._gossip_watermark(k)

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, ClientRequest):
            self._handle_client_request_batch(
                src, ClientRequestBatch(CommandBatch((message.command,))),
                from_client=True)
        elif isinstance(message, ClientRequestArray):
            self._handle_client_request_array(src, message)
        elif type(message).__name__ == "IngestRun":
            self._handle_ingest_run(src, message)
        elif isinstance(message, ClientRequestBatch):
            self._handle_client_request_batch(src, message,
                                              from_client=False)
        elif isinstance(message, HighWatermark):
            self._handle_high_watermark(src, message)
        elif isinstance(message, LeaderInfoRequestClient):
            if self.state != ("inactive",):
                self.send(src, LeaderInfoReplyClient(self.group_index,
                                                     self.round))
        elif isinstance(message, LeaderInfoRequestBatcher):
            if self.state != ("inactive",):
                self.send(src, LeaderInfoReplyBatcher(self.group_index,
                                                      self.round))
        elif isinstance(message, Nack):
            self._handle_nack(src, message)
        elif isinstance(message, ChosenWatermark):
            self.chosen_watermark = max(self.chosen_watermark, message.slot)
            if self.admission is not None:
                # Drain-granular release (see the multipaxos leader).
                self._sync_inflight()
        elif isinstance(message, Recover):
            self._handle_recover(src, message)
        elif isinstance(message, Reconfigure):
            self._handle_reconfigure(src, message)
        elif isinstance(message, EpochAck):
            self._handle_epoch_ack(src, message)
        elif isinstance(message, EpochCommit):
            self._handle_epoch_commit(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _adopt_epochs(self, commits) -> bool:
        """Merge Phase1b-discovered epoch entries (highest round per
        id); True when coverage changed."""
        changed = False
        for commit in sorted(commits, key=lambda c: (c.epoch, c.round)):
            try:
                outcome = self.epochs.offer(
                    EpochConfig(epoch=commit.epoch,
                                start_slot=commit.start_slot,
                                f=commit.f, members=commit.members),
                    commit.round)
            except ValueError as e:
                self.logger.warn(f"discovered epoch rejected: {e}")
                continue
            changed = changed or outcome in ("new", "replaced")
        return changed

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1):
            return
        phase1 = self.state
        if phase1b.round != self.round:
            self.logger.check_lt(phase1b.round, self.round)
            return
        phase1.by_addr[src] = phase1b
        if self.epochs is not None and phase1b.epochs \
                and self._adopt_epochs(phase1b.epochs):
            members: dict = {}
            for config in self._phase1_epochs():
                members.update(dict.fromkeys(config.members))
            for acceptor in members:
                if acceptor not in phase1.by_addr:
                    self.send(acceptor, phase1.phase1a)
        if self.epochs is not None and self.epochs.multi_epoch:
            # Phase1-with-both-configs over this group's epochs.
            answered = set(phase1.by_addr)
            for config in self._phase1_epochs():
                if not config.has_read_quorum(answered):
                    return
        else:
            phase1.phase1bs[phase1b.group_index][phase1b.acceptor_index] \
                = phase1b
            if any(len(g) < self.config.quorum_size
                   for g in phase1.phase1bs):
                return

        max_slot = max(
            (info.slot for p1b in phase1.by_addr.values()
             for info in p1b.info),
            default=-1)
        max_slot = max(max_slot, phase1.recover_slot)
        self.logger.check(
            max_slot == -1
            or self.slot_system.leader(max_slot) == self.group_index)

        # Fill only the slots this group owns (Leader.scala:624-647).
        start = self.slot_system.next_classic_round(
            self.group_index, self.chosen_watermark - 1)
        multi = self.epochs is not None and self.epochs.multi_epoch
        for slot in range(start, max_slot + 1,
                          self.config.num_leader_groups):
            if multi:
                # Scan every answering acceptor: non-members of the
                # slot's epoch hold no votes for it, so this is a
                # superset of the right epoch's read quorum.
                voters = phase1.by_addr.values()
            else:
                voters = phase1.phase1bs[
                    self._acceptor_group_index_by_slot(slot)].values()
            self.send(self._proxy_leader(),
                      Phase2a(slot=slot, round=self.round,
                              value=self._safe_value(voters, slot)))
        # next_slot must clear the chosen watermark as well as the
        # voted max: Phase1bs report nothing below the watermark (all
        # chosen -- e.g. a predecessor's ChosenNoopRange), so with no
        # votes above it this would re-propose a pending command into
        # an already-Noop-chosen slot -- a second chosen value (found
        # by the WAL chaos soak's partition + leader-churn schedules).
        # Chosen slots >= the watermark are covered by quorum
        # intersection: some Phase1b carries their vote.
        self.next_slot = self.slot_system.next_classic_round(
            self.group_index, max(max_slot, self.chosen_watermark - 1))
        phase1.resend_phase1as.stop()
        self.state = ("phase2",)
        if multi:
            # Re-drive the newest epoch's commit before proposing into
            # it: untagged runs may only flow once every proxy provably
            # routes by the current epoch map, and the epoch's durable
            # predecessor-quorum must exist (proven by Phase1bs, or
            # re-established by the gated acks below). Pending batches
            # buffer through the activation window.
            newest = self.epochs.current()
            pred = self.epochs.config(newest.epoch - 1)
            reporters = {
                addr for addr, p1b in phase1.by_addr.items()
                if any(c.epoch == newest.epoch for c in p1b.epochs)}
            # Proof of durable commitment: a predecessor write quorum
            # among the reporters, or a slot chosen STRICTLY past the
            # activation watermark (chosen under the epoch => some
            # gate-compliant leader activated it; WALs outlive
            # crashes).
            proven = (pred is None
                      or pred.has_write_quorum(reporters)
                      or self.chosen_watermark > newest.start_slot)
            self._start_epoch_commit(newest, recommit=True,
                                     need_old_quorum=not proven)
        for batch in phase1.pending_batches:
            self._process_batch(batch)
        # The backlog just moved into the span; resync so it isn't
        # double-counted.
        self._admitted_backlog = 0
        if self.admission is not None:
            self._sync_inflight()

    def _sync_inflight(self) -> None:
        """Resync to the live in-flight measure: this group's
        owned-slot span plus the Phase1 backlog (see the multipaxos
        leader's _sync_inflight for why the backlog must count)."""
        stride = self.config.num_leader_groups
        self.admission.set_inflight(
            (self.next_slot - self.chosen_watermark) // stride
            + self._admitted_backlog)

    def _admit(self, message, n: int) -> bool:
        """paxload admission (the multipaxos leader's _admit, with
        this group's owned-slot span as the in-flight measure)."""
        admission = self.admission
        if admission is None:
            return True
        if admission.admit(n):
            return True
        from frankenpaxos_tpu.serve.admission import reject_replies_for

        for client, reply in reject_replies_for(
                message, admission.retry_after_ms(),
                admission.last_reason):
            self.send(client, reply)
        return False

    def _handle_client_request_batch(self, src: Address,
                                     batch: ClientRequestBatch,
                                     from_client: bool) -> None:
        if self.state == ("inactive",):
            if from_client:
                self.send(src, NotLeaderClient(self.group_index))
            else:
                self.send(src, NotLeaderBatcher(self.group_index, batch))
        elif not self._admit(batch, len(batch.batch.commands)):
            pass
        elif isinstance(self.state, _Phase1):
            self._admitted_backlog += len(batch.batch.commands)
            self.state.pending_batches.append(batch)
        else:
            self._process_batch(batch)

    def _handle_client_request_array(self, src: Address,
                                     array: ClientRequestArray) -> None:
        """The client edge of the drain-granular run pipeline: every
        command gets its OWN owned slot (transport-level coalescing,
        not slot sharing -- see multipaxos ClientRequestArray)."""
        if not array.commands:
            return
        if self.state == ("inactive",):
            self.send(src, NotLeaderClient(self.group_index))
            return
        commands = array.commands
        if self.admission is not None:
            commands = self._admit_prefix(commands)
            if not commands:
                return
            if len(commands) < len(array.commands):
                array = ClientRequestArray(commands=commands)
        if isinstance(self.state, _Phase1):
            self._admitted_backlog += len(commands)
            for command in commands:
                self.state.pending_batches.append(
                    ClientRequestBatch(CommandBatch((command,))))
        else:
            self._process_request_array(array)

    def _admit_prefix(self, commands: tuple) -> tuple:
        """Partial admission for a coalesced array (see the multipaxos
        leader's _admit_prefix)."""
        admission = self.admission
        k = admission.admit_up_to(len(commands))
        if k < len(commands):
            from frankenpaxos_tpu.serve.admission import reject_replies_for

            for address, reply in reject_replies_for(
                    ClientRequestArray(commands=commands[k:]),
                    retry_after_ms=admission.retry_after_ms(),
                    reason=admission.last_reason):
                self.send(address, reply)
        return commands[:k]

    def _handle_high_watermark(self, src: Address,
                               message: HighWatermark) -> None:
        """Skip our slots if we're lagging (Leader.scala:717-770)."""
        self.high_watermark = max(self.next_slot, self.high_watermark)
        if message.next_slot <= self.high_watermark:
            return
        self.high_watermark = message.next_slot
        if self.state != ("phase2",):
            return
        if self.high_watermark - self.next_slot \
                < self.send_noop_range_if_lagging_by:
            return
        change = self._epoch_change
        if change is not None and not change.activated:
            # Mid-handover: don't skip slots whose epoch is still
            # committing; a later HighWatermark re-triggers.
            return
        end = self.slot_system.next_classic_round(self.group_index,
                                                  self.high_watermark)
        at = self.next_slot
        while at < end:
            seg_end = end
            if self.epochs is not None:
                # Split the skip range at epoch activation boundaries:
                # each segment's noop quorum is one epoch's.
                config = self.epochs.epoch_of_slot(at)
                nxt = self.epochs.config(config.epoch + 1)
                if nxt is not None:
                    seg_end = min(end, nxt.start_slot)
            self.send(self._proxy_leader(),
                      Phase2aNoopRange(slot_start_inclusive=at,
                                       slot_end_exclusive=seg_end,
                                       round=self.round))
            at = seg_end
        self.next_slot = end

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        if nack.round <= self.round:
            return
        if self.state == ("inactive",):
            self.round = nack.round
        else:
            self.round = self.round_system.next_classic_round(self.index,
                                                              nack.round)
            self.leader_change(is_new_leader=True, recover_slot=-1)

    def _handle_recover(self, src: Address, recover: Recover) -> None:
        # A hole in one group's slots can only be fixed by that group
        # (Leader.scala:845-869); recover_slot threads through phase 1.
        if self.slot_system.leader(recover.slot) != self.group_index:
            return
        if self.state != ("inactive",):
            self.leader_change(is_new_leader=True,
                               recover_slot=recover.slot)

    # --- reconfiguration (reconfig/, docs/RECONFIG.md) --------------------
    def _start_epoch_commit(self, config: EpochConfig, recommit: bool,
                            need_old_quorum: bool = True) -> None:
        """Drive one EpochCommit to quorum: broadcast + resend until
        the activation set (f+1 PREDECESSOR-epoch members -- unless
        Phase1 already proved that durability -- and, because Mencius
        runs are untagged, EVERY proxy leader) has acked. ``recommit``
        re-drives an adopted epoch after failover: the store already
        holds it, but this leader must not propose into it before the
        proxies provably route by it and the durable discovery quorum
        provably exists."""
        commit = EpochCommit(epoch=config.epoch,
                             start_slot=config.start_slot,
                             f=config.f, round=self.round,
                             members=config.members)
        old = (self.epochs.config(config.epoch - 1)
               if need_old_quorum else None)
        targets: dict = dict.fromkeys(old.members if old else ())
        targets.update(dict.fromkeys(config.members))
        targets.update(dict.fromkeys(self.config.proxy_leader_addresses))
        targets.update(dict.fromkeys(
            a for a in self.config.leader_addresses[self.group_index]
            if a != self.address))

        def resend():
            change = self._epoch_change
            if change is None or change.config is not config:
                return
            for dst in change.targets:
                if dst not in change.acks:
                    self.send(dst, change.commit)
            timer.start()

        timer = self.timer("resendEpochCommit", 1.0, resend)
        timer.start()
        self._epoch_change = _EpochChange(
            config=config, commit=commit, targets=set(targets),
            acks=set(), resend=timer, pending=[], recommit=recommit,
            need_old_quorum=need_old_quorum)
        if recommit:
            self.epochs.offer(config, self.round)
        for dst in targets:
            self.send(dst, commit)

    def _handle_reconfigure(self, src: Address,
                            msg: Reconfigure) -> None:
        if self.epochs is None:
            self.logger.warn(
                "Reconfigure ignored: this leader group has multiple "
                "acceptor groups (epoch-frozen)")
            return
        if self.state != ("phase2",):
            self.logger.debug("Reconfigure ignored outside phase2")
            return
        if self._epoch_change is not None:
            if not self._epoch_change.activated:
                self.logger.debug(
                    "Reconfigure ignored: a change is mid-activation")
                return
            # The previous change is ACTIVE and only chasing straggler
            # acks (possibly of dead members); the new change's commit
            # flow supersedes those resends.
            self._abort_epoch_change()
        current = self.epochs.current()
        members = tuple(msg.members)
        if members == current.members:
            return
        if self.next_slot < current.start_slot:
            self.logger.debug("Reconfigure ignored: next_slot below "
                              "the current epoch's start")
            return
        try:
            config = EpochConfig(epoch=current.epoch + 1,
                                 start_slot=self.next_slot,
                                 f=self.config.f, members=members)
        except ValueError as e:
            self.logger.warn(f"Reconfigure rejected: {e}")
            return
        self._start_epoch_commit(config, recommit=False)

    def _epoch_activation_ready(self, change) -> bool:
        proxies = set(self.config.proxy_leader_addresses)
        if not proxies <= change.acks:
            return False
        if not change.need_old_quorum:
            return True  # durability already proven via Phase1bs
        old = self.epochs.config(change.config.epoch - 1)
        return old is None or old.has_write_quorum(change.acks)

    def _handle_epoch_ack(self, src: Address, ack: EpochAck) -> None:
        change = self._epoch_change
        if change is None or ack.epoch != change.config.epoch \
                or ack.round != self.round:
            return
        change.acks.add(src)
        if not change.activated and self._epoch_activation_ready(change):
            try:
                self.epochs.offer(change.config, self.round)
            except ValueError as e:
                self.logger.warn(f"epoch activation aborted: {e}")
                self._abort_epoch_change()
                return
            change.activated = True
            # Stop chasing old-epoch/peer-leader stragglers once
            # activated (the reconfigured-OUT member may be dead
            # forever); proxies and new members still matter.
            change.targets &= (set(self.config.proxy_leader_addresses)
                               | set(change.config.members))
            pending, change.pending = change.pending, []
            for batch in pending:
                self._process_batch(batch)
        if change.activated and change.targets <= change.acks:
            change.resend.stop()
            self._epoch_change = None

    def _handle_epoch_commit(self, src: Address,
                             commit: EpochCommit) -> None:
        """A peer leader's commit: adopt and ack."""
        if self.epochs is None:
            return
        if self.slot_system.leader(commit.start_slot) != self.group_index:
            return  # another group's epoch space
        try:
            outcome = self.epochs.offer(
                EpochConfig(epoch=commit.epoch,
                            start_slot=commit.start_slot,
                            f=commit.f, members=commit.members),
                commit.round)
        except ValueError as e:
            self.logger.warn(f"peer EpochCommit rejected: {e}")
            return
        if outcome in ("new", "replaced", "dup"):
            self.send(src, EpochAck(epoch=commit.epoch,
                                    round=commit.round))
        if outcome in ("new", "replaced") and self.state == ("phase2",):
            # An active leader adopting a peer's epoch mid-phase2:
            # gate its own proposals on the durable-commit proof, as
            # in the post-Phase1 path (no Phase1b reporters here).
            newest = self.epochs.current()
            self._abort_epoch_change()
            self._start_epoch_commit(
                newest, recommit=True,
                need_old_quorum=(
                    self.chosen_watermark <= newest.start_slot))


class MenciusProxyLeader(Actor):
    """(mencius/ProxyLeader.scala:31-420)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        # (start, end, round) -> pending state; None once Done.
        self.states: dict[tuple, object] = {}
        # Pending strided Phase2aRuns: start -> [round, stride, values,
        # acks set]. One O(1) record per run; round-monotone (a
        # same-start higher-round run evicts its predecessor).
        self._runs: dict[int, list] = {}
        # Retired / evicted run rounds: start -> set of rounds, for the
        # stray-ack check.
        self._done_runs: dict[int, set] = {}
        # Reconfiguration (reconfig/): one epoch store per
        # single-acceptor-group leader group; quorums for its slots
        # resolve through it (PAX110) and acks count by ADDRESS
        # membership in the slot's epoch.
        self.epochs: dict[int, EpochStore] = {}
        for lg, groups in enumerate(config.acceptor_addresses):
            if len(groups) == 1:
                self.epochs[lg] = EpochStore.from_members(
                    tuple(groups[0]), config.f)

    # A GROUP-COUNT read for the striping arithmetic, not a membership
    # read; group counts are structural (reconfig swaps members within
    # the single group).
    def _acceptor_group_index_by_slot(self, leader_group: int,  # paxlint: disable=PAX110
                                      slot: int) -> int:
        return ((slot // self.config.num_leader_groups)
                % len(self.config.acceptor_addresses[leader_group]))

    def _epoch_for_slot(self, slot: int) -> "EpochConfig | None":
        store = self.epochs.get(self.slot_system.leader(slot))
        return store.epoch_of_slot(slot) if store is not None else None

    def _handle_epoch_commit(self, src: Address,
                             commit: EpochCommit) -> None:
        store = self.epochs.get(self.slot_system.leader(commit.start_slot))
        if store is None:
            return
        try:
            outcome = store.offer(
                EpochConfig(epoch=commit.epoch,
                            start_slot=commit.start_slot,
                            f=commit.f, members=commit.members),
                commit.round)
        except ValueError as e:
            self.logger.warn(f"EpochCommit rejected: {e}")
            return
        if outcome == "stale":
            return
        self.send(src, EpochAck(epoch=commit.epoch, round=commit.round))

    def receive(self, src: Address, message) -> None:
        if isinstance(message, HighWatermark):
            # Relay to every leader of every group
            # (ProxyLeader.scala:207-214).
            for leader in self.config.all_leaders():
                self.send(leader, message)
        elif isinstance(message, EpochCommit):
            self._handle_epoch_commit(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Phase2aRun):
            self._handle_phase2a_run(src, message)
        elif isinstance(message, Phase2bRun):
            self._handle_phase2b_run(src, message)
        elif isinstance(message, Phase2aNoopRange):
            self._handle_phase2a_noop_range(src, message)
        elif isinstance(message, Phase2bNoopRange):
            self._handle_phase2b_noop_range(src, message)
        else:
            self.logger.fatal(f"unexpected proxy leader message {message!r}")

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        key = (phase2a.slot, phase2a.slot + 1, phase2a.round)
        if key in self.states:
            return
        config = self._epoch_for_slot(phase2a.slot)
        if config is not None:
            quorum = self.rng.sample(list(config.members),
                                     config.quorum_size)
        else:
            leader_group = self.slot_system.leader(phase2a.slot)
            # Multi-acceptor-group striping is epoch-frozen.
            # paxlint: disable=PAX110
            group = self.config.acceptor_addresses[leader_group][
                self._acceptor_group_index_by_slot(leader_group,
                                                   phase2a.slot)]
            quorum = self.rng.sample(list(group),
                                     self.config.quorum_size)
        for acceptor in quorum:
            self.send(acceptor, phase2a)
        self.states[key] = {"phase2a": phase2a, "phase2bs": {}}

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        key = (phase2b.slot, phase2b.slot + 1, phase2b.round)
        state = self.states.get(key)
        if key not in self.states:
            self.logger.fatal(f"Phase2b for unknown {key}")
        if state is None or "phase2a" not in state:
            return  # Done or a noop-range entry
        config = self._epoch_for_slot(phase2b.slot)
        if config is not None:
            # Address-keyed membership counting: a replacement can
            # reuse a dead member's (group, index) coordinates, its
            # address it cannot.
            if src not in config.members:
                return
            state["phase2bs"][src] = phase2b
        else:
            state["phase2bs"][phase2b.acceptor_index] = phase2b
        if len(state["phase2bs"]) < self.config.quorum_size:
            return
        for replica in self.config.replica_addresses:
            self.send(replica, Chosen(slot=phase2b.slot,
                                      value=state["phase2a"].value))
        self.states[key] = None  # Done

    def _handle_phase2a_run(self, src: Address, run: Phase2aRun) -> None:
        """One write quorum for the whole strided run (one thrifty f+1
        sample, one forwarded message per member, one O(1) record).
        Slots of a strided leader-group run all live in ONE acceptor
        group only when that group is alone; otherwise decompose to the
        per-slot path (the leader already avoids sending runs then)."""
        k = len(run.values)
        if k == 0:
            return
        leader_group = self.slot_system.leader(run.start_slot)
        # paxlint: disable=PAX110 -- group-COUNT read (structural):
        # multi-group striping decomposes to the per-slot path.
        if len(self.config.acceptor_addresses[leader_group]) > 1:
            for i, value in enumerate(run.values):
                self._handle_phase2a(src, Phase2a(
                    slot=run.start_slot + i * run.stride,
                    round=run.round, value=value))
            return
        pending = self._runs.get(run.start_slot)
        if pending is not None:
            if run.round <= pending[0]:
                return  # duplicate (same or stale round)
            # Round-monotone eviction, mirroring the acceptor: the
            # higher-round re-proposal wins; remember the evicted round
            # so its straggler acks are recognized.
            self._done_runs.setdefault(run.start_slot,
                                       set()).add(pending[0])
        config = self._epoch_for_slot(run.start_slot)
        if config is not None:
            # A run never spans epochs (the leader buffers through the
            # handover), so the start slot's epoch covers it all.
            quorum = self.rng.sample(list(config.members),
                                     config.quorum_size)
        else:
            # paxlint: disable=PAX110 -- multi-group striping is frozen
            group = self.config.acceptor_addresses[leader_group][0]
            quorum = self.rng.sample(list(group),
                                     self.config.quorum_size)
        for acceptor in quorum:
            self.send(acceptor, run)  # encode the values ONCE
        self._runs[run.start_slot] = [run.round, run.stride,
                                      run.values, set()]

    def _handle_phase2b_run(self, src: Address,
                            phase2b: Phase2bRun) -> None:
        """Acceptors vote runs atomically, so quorum tracking is
        run-granular: count distinct acceptors, emit ONE ChosenRun per
        replica when f+1 acked."""
        run = self._runs.get(phase2b.start_slot)
        if run is None or run[0] != phase2b.round:
            if phase2b.round in self._done_runs.get(phase2b.start_slot,
                                                    ()):
                return  # straggler ack of a retired/evicted run
            if run is None:
                self.logger.fatal(
                    f"Phase2bRun for unknown run at {phase2b.start_slot}")
            return  # stale-round ack of a live re-proposed run
        round, stride, values, acks = run
        config = self._epoch_for_slot(phase2b.start_slot)
        if config is not None:
            if src not in config.members:
                return  # not this epoch's vote
            acks.add(src)
        else:
            acks.add(phase2b.acceptor_index)
        if len(acks) < self.config.quorum_size:
            return
        for replica in self.config.replica_addresses:
            self.send(replica, ChosenRun(start_slot=phase2b.start_slot,
                                         stride=stride, values=values))
        del self._runs[phase2b.start_slot]
        self._done_runs.setdefault(phase2b.start_slot, set()).add(round)

    def _handle_phase2a_noop_range(self, src: Address,
                                   phase2a: Phase2aNoopRange) -> None:
        key = (phase2a.slot_start_inclusive, phase2a.slot_end_exclusive,
               phase2a.round)
        if key in self.states:
            return
        leader_group = self.slot_system.leader(phase2a.slot_start_inclusive)
        config = self._epoch_for_slot(phase2a.slot_start_inclusive)
        if config is not None:
            # The leader splits skip ranges at epoch boundaries, so the
            # start slot's epoch covers the whole range.
            for acceptor in self.rng.sample(list(config.members),
                                            config.quorum_size):
                self.send(acceptor, phase2a)
            self.states[key] = {"noop_range": phase2a,
                                "phase2bs_per_group": [{}]}
            return
        # paxlint: disable=PAX110 -- multi-group striping is frozen
        for group in self.config.acceptor_addresses[leader_group]:
            for acceptor in self.rng.sample(list(group),
                                            self.config.quorum_size):
                self.send(acceptor, phase2a)
        self.states[key] = {
            "noop_range": phase2a,
            "phase2bs_per_group": [
                {} for _ in self.config.acceptor_addresses[leader_group]],
        }

    def _handle_phase2b_noop_range(self, src: Address,
                                   phase2b: Phase2bNoopRange) -> None:
        key = (phase2b.slot_start_inclusive, phase2b.slot_end_exclusive,
               phase2b.round)
        state = self.states.get(key)
        if key not in self.states:
            self.logger.fatal(f"Phase2bNoopRange for unknown {key}")
        if state is None or "noop_range" not in state:
            return
        config = self._epoch_for_slot(phase2b.slot_start_inclusive)
        if config is not None:
            if src not in config.members:
                return
            state["phase2bs_per_group"][0][src] = phase2b
        else:
            state["phase2bs_per_group"][phase2b.acceptor_group_index][
                phase2b.acceptor_index] = phase2b
        if any(len(g) < self.config.quorum_size
               for g in state["phase2bs_per_group"]):
            return
        for replica in self.config.replica_addresses:
            self.send(replica, ChosenNoopRange(
                slot_start_inclusive=phase2b.slot_start_inclusive,
                slot_end_exclusive=phase2b.slot_end_exclusive))
        self.states[key] = None  # Done


@dataclasses.dataclass
class _VoteState:
    vote_round: int
    vote_value: object


class MenciusAcceptor(Actor, DurableRole):
    """(mencius/Acceptor.scala:103-300)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig, wal=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.leader_group_index, self.acceptor_group_index, self.index = next(
            (lg, ag, i)
            for lg, groups in enumerate(config.acceptor_addresses)
            for ag, group in enumerate(groups)
            for i, a in enumerate(group)
            if a == address)
        self.round_system = ClassicRoundRobin(
            len(config.leader_addresses[self.leader_group_index]))
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        self.round = -1
        self.states: SortedDict = SortedDict()
        # Run-voted state (Phase2aRun): start -> (count, stride, round,
        # values) -- one O(1) record per strided run. A slot's
        # authoritative vote is the HIGHEST round across both stores
        # (see _voted_info); the acceptor's monotone ``round`` makes
        # max-round resolution exact.
        self._voted_runs: SortedDict = SortedDict()
        self.max_voted_slot = -1
        # Committed reconfiguration epochs (reconfig/): epoch id ->
        # EpochCommit, round-monotone; WAL'd before the ack leaves and
        # reported in every Phase1b (the matchmaker role -- see the
        # multipaxos acceptor).
        self._epoch_commits: dict[int, EpochCommit] = {}
        # Durability (wal/): the multipaxos acceptor's group-commit
        # contract, strided -- promises/votes/runs/noop-ranges append
        # to the WAL and every dependent ack holds back until
        # on_drain's single fsync releases it (DurableRole).
        self._wal_init(wal)
        if wal is not None:
            self._recover_from_wal()

    # --- durability -------------------------------------------------------
    def _recover_from_wal(self) -> None:
        for record in self.wal.recover(self.logger):
            if isinstance(record, WalSnapshot):
                self.round = -1
                self.states.clear()
                self._voted_runs.clear()
                self.max_voted_slot = -1
            elif isinstance(record, WalPromise):
                self.round = max(self.round, record.round)
            elif isinstance(record, WalVote):
                self.round = max(self.round, record.round)
                self.states[record.slot] = _VoteState(
                    record.round, decode_value(record.value))
                self.max_voted_slot = max(self.max_voted_slot,
                                          record.slot)
            elif isinstance(record, WalVoteRun):
                self.round = max(self.round, record.round)
                self._store_run(record.start_slot, record.stride,
                                record.round,
                                decode_value_array(record.values))
            elif isinstance(record, WalNoopRange):
                self.round = max(self.round, record.round)
                self._store_noop_range(record.slot_start_inclusive,
                                       record.slot_end_exclusive,
                                       record.round)
            elif isinstance(record, WalEpoch):
                epoch, start, f, rnd, members = decode_epoch_config(
                    record.payload)
                known = self._epoch_commits.get(epoch)
                if known is None or rnd > known.round:
                    self._epoch_commits[epoch] = EpochCommit(
                        epoch=epoch, start_slot=start, f=f, round=rnd,
                        members=members)
            else:
                self.logger.fatal(
                    f"unexpected acceptor WAL record {record!r}")

    def _wal_compact(self) -> None:
        records = [WalPromise(round=self.round)]
        for epoch in sorted(self._epoch_commits):
            c = self._epoch_commits[epoch]
            records.append(WalEpoch(payload=encode_epoch_config(
                c.epoch, c.start_slot, c.f, c.round, c.members)))
        for start, (count, stride, rnd, values) in \
                self._voted_runs.items():
            records.append(WalVoteRun(
                start_slot=start, stride=stride, round=rnd,
                values=encode_value_array(values)))
        for slot, vs in self.states.items():
            records.append(WalVote(
                slot=slot, round=vs.vote_round,
                value=encode_value(vs.vote_value)))
        self.wal.compact(WalSnapshot(payload=b""), records)

    def on_drain(self) -> None:
        self._wal_drain()  # group commit, then release the held acks

    def _nack_leader(self, round: int, slot: int) -> Address:
        return self.config.leader_addresses[self.slot_system.leader(slot)][
            self.round_system.leader(round)]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        elif isinstance(message, Phase2aRun):
            self._handle_phase2a_run(src, message)
        elif isinstance(message, Phase2aNoopRange):
            self._handle_phase2a_noop_range(src, message)
        elif isinstance(message, EpochCommit):
            self._handle_epoch_commit(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_epoch_commit(self, src: Address,
                             commit: EpochCommit) -> None:
        """The matchmaker write (see the multipaxos acceptor): store
        round-monotonically, WAL, ack after the group commit."""
        if commit.round < self.round:
            self.send(src, Nack(round=self.round))
            return
        known = self._epoch_commits.get(commit.epoch)
        if known is None or commit.round > known.round:
            self._epoch_commits[commit.epoch] = commit
            if self.wal is not None and known != commit:
                self.wal.append(WalEpoch(payload=encode_epoch_config(
                    commit.epoch, commit.start_slot, commit.f,
                    commit.round, commit.members)))
        elif known is not None and commit.round == known.round \
                and known != commit:
            self.logger.fatal(
                f"conflicting EpochCommits at one round: {known!r} "
                f"vs {commit!r}")
        self._wal_send(src, EpochAck(epoch=commit.epoch,
                                     round=commit.round))

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round < self.round:
            self.send(src, Nack(round=self.round))
            return
        if self.wal is not None and phase1a.round > self.round:
            self.wal.append(WalPromise(round=phase1a.round))
        self.round = phase1a.round
        self._wal_send(src, Phase1b(group_index=self.acceptor_group_index,
                                    acceptor_index=self.index,
                                    round=self.round,
                                    info=self._voted_info(
                                        phase1a.chosen_watermark),
                                    epochs=tuple(
                                        self._epoch_commits[e]
                                        for e in sorted(
                                            self._epoch_commits))))

    def _voted_info(self, minimum: int) -> tuple:
        """Every voted slot >= ``minimum`` with its HIGHEST-round vote,
        merging the per-slot store and the strided run store (a
        failover that ignored run votes would recover Noop over
        accepted values -- data loss). Recovery-only cold path: runs
        expand per slot here and nowhere else."""
        best: dict[int, tuple] = {
            slot: (self.states[slot].vote_round,
                   self.states[slot].vote_value)
            for slot in self.states.irange(minimum=minimum)}
        for start, (count, stride, rnd, values) in \
                self._voted_runs.items():
            if start + (count - 1) * stride < minimum:
                continue
            for i in range(count):
                slot = start + i * stride
                if slot < minimum:
                    continue
                cur = best.get(slot)
                if cur is None or rnd > cur[0]:
                    best[slot] = (rnd, values[i])
        return tuple(
            Phase1bSlotInfo(slot=slot, vote_round=rnd, vote_value=value)
            for slot, (rnd, value) in sorted(best.items()))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            self.send(self._nack_leader(phase2a.round, phase2a.slot),
                      Nack(round=self.round))
            return
        self.round = phase2a.round
        self.states[phase2a.slot] = _VoteState(self.round, phase2a.value)
        self.max_voted_slot = max(self.max_voted_slot, phase2a.slot)
        if self.wal is not None:
            self.wal.append(WalVote(
                slot=phase2a.slot, round=self.round,
                value=encode_value(phase2a.value)))
        self._wal_send(src, Phase2b(group_index=self.acceptor_group_index,
                                    acceptor_index=self.index,
                                    slot=phase2a.slot, round=self.round))

    def _handle_phase2a_run(self, src: Address, run: Phase2aRun) -> None:
        """A whole strided proposal run in one O(1) update: one round
        check, one run record, one Phase2bRun ack -- the per-drain
        shape of the per-slot _handle_phase2a."""
        if run.round < self.round:
            self.send(self._nack_leader(run.round, run.start_slot),
                      Nack(round=self.round))
            return
        self.round = run.round
        count = self._store_run(run.start_slot, run.stride, run.round,
                                run.values)
        if self.wal is not None:
            # A raw copy of the inbound lazy value segment, never a
            # re-materialization.
            self.wal.append(WalVoteRun(
                start_slot=run.start_slot, stride=run.stride,
                round=run.round,
                values=encode_value_array(run.values)))
        self._wal_send(src, Phase2bRun(
            acceptor_group_index=self.acceptor_group_index,
            acceptor_index=self.index, start_slot=run.start_slot,
            count=count, stride=run.stride, round=run.round))

    def _store_run(self, start_slot: int, stride: int, round: int,
                   values) -> int:
        """Merge one strided voted run into the run store; returns the
        run's count. Shared by the live Phase2aRun handler and WAL
        replay so truncation-tail semantics cannot drift."""
        count = len(values)
        old = self._voted_runs.get(start_slot)
        self._voted_runs[start_slot] = (count, stride, round, values)
        if old is not None and old[1] == stride and old[0] > count:
            # Same-start truncation (the multipaxos acceptor's tail
            # fix, strided): reinsert the longer predecessor's
            # non-overlapped voted tail so Phase1 recovery keeps it.
            old_count, old_stride, old_round, old_values = old
            tail_start = start_slot + count * stride
            if self._voted_runs.get(tail_start) is None:
                self._voted_runs[tail_start] = (
                    old_count - count, stride, old_round,
                    old_values[count:])
            else:
                for i in range(count, old_count):
                    slot = start_slot + i * stride
                    cur = self.states.get(slot)
                    if cur is None or cur.vote_round < old_round:
                        self.states[slot] = _VoteState(old_round,
                                                       old_values[i])
        self.max_voted_slot = max(
            self.max_voted_slot,
            start_slot + (count - 1) * stride)
        return count

    def _handle_phase2a_noop_range(self, src: Address,
                                   phase2a: Phase2aNoopRange) -> None:
        """Vote noop for every slot in the range owned by this acceptor
        group (Acceptor.scala:237-293)."""
        if phase2a.round < self.round:
            self.send(self._nack_leader(phase2a.round,
                                        phase2a.slot_start_inclusive),
                      Nack(round=self.round))
            return
        self.round = phase2a.round
        self._store_noop_range(phase2a.slot_start_inclusive,
                               phase2a.slot_end_exclusive, self.round)
        if self.wal is not None:
            # One O(1) record for the whole range; replay re-derives
            # the owned slots from the (restart-stable) config.
            self.wal.append(WalNoopRange(
                slot_start_inclusive=phase2a.slot_start_inclusive,
                slot_end_exclusive=phase2a.slot_end_exclusive,
                round=self.round))
        self._wal_send(src, Phase2bNoopRange(
            acceptor_group_index=self.acceptor_group_index,
            acceptor_index=self.index,
            slot_start_inclusive=phase2a.slot_start_inclusive,
            slot_end_exclusive=phase2a.slot_end_exclusive,
            round=self.round))

    def _store_noop_range(self, start_inclusive: int, end_exclusive: int,
                          round: int) -> None:
        """Vote noop for every slot this acceptor group owns in the
        range. Shared by the live handler and WAL replay."""
        num_groups = len(
            self.config.acceptor_addresses[self.leader_group_index])
        stride = self.config.num_leader_groups * num_groups
        start = start_inclusive
        while (start < end_exclusive
               and ((start // self.config.num_leader_groups) % num_groups)
               != self.acceptor_group_index):
            start += self.config.num_leader_groups
        for slot in range(start, end_exclusive, stride):
            self.states[slot] = _VoteState(round, NOOP)
