"""Mencius Batcher, Leader, ProxyLeader, and Acceptor.

Reference behavior: mencius/Batcher.scala:85-190, Leader.scala:130-870,
ProxyLeader.scala:31-420, Acceptor.scala:103-300.
"""

from __future__ import annotations

import dataclasses
import random

try:
    from sortedcontainers import SortedDict  # type: ignore[import-untyped]
except ImportError:  # stripped environments: pure-Python fallback
    from frankenpaxos_tpu.utils.sorted_compat import SortedDict

from frankenpaxos_tpu.election.basic import ElectionOptions, ElectionParticipant
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.wal import (
    DurableRole,
    WalNoopRange,
    WalPromise,
    WalSnapshot,
    WalVote,
    WalVoteRun,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    decode_value,
    decode_value_array,
    encode_value,
    encode_value_array,
)
from frankenpaxos_tpu.protocols.mencius.common import (
    NOOP,
    Chosen,
    ChosenNoopRange,
    ChosenRun,
    ChosenWatermark,
    ClientRequest,
    ClientRequestArray,
    ClientRequestBatch,
    CommandBatch,
    DistributionScheme,
    HighWatermark,
    LeaderInfoReplyBatcher,
    LeaderInfoReplyClient,
    LeaderInfoRequestBatcher,
    LeaderInfoRequestClient,
    MenciusConfig,
    Nack,
    NotLeaderBatcher,
    NotLeaderClient,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2aNoopRange,
    Phase2aRun,
    Phase2b,
    Phase2bNoopRange,
    Phase2bRun,
    Recover,
)


class MenciusBatcher(Actor):
    """(Batcher.scala:85-190): batch, then send to the current round's
    leader of a random leader group (Hash) or the colocated group."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig,
                 batch_size: int = 1, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.batch_size = batch_size
        self.rng = random.Random(seed)
        self.index = (list(config.batcher_addresses).index(address)
                      if address in config.batcher_addresses else 0)
        # Known round per leader group.
        self.rounds = [0] * config.num_leader_groups
        self.growing_batch: list = []
        self.pending_resend_batches: list = []

    def _group_leader(self, group: int) -> Address:
        rs = ClassicRoundRobin(len(self.config.leader_addresses[group]))
        return self.config.leader_addresses[group][
            rs.leader(self.rounds[group])]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self.growing_batch.append(message.command)
            if len(self.growing_batch) >= self.batch_size:
                if (self.config.distribution_scheme
                        == DistributionScheme.HASH):
                    group = self.rng.randrange(
                        self.config.num_leader_groups)
                else:
                    group = self.index % self.config.num_leader_groups
                self.send(self._group_leader(group), ClientRequestBatch(
                    CommandBatch(tuple(self.growing_batch))))
                self.growing_batch.clear()
        elif isinstance(message, NotLeaderBatcher):
            self.pending_resend_batches.append(
                (message.leader_group_index, message.client_request_batch))
            for leader in self.config.leader_addresses[
                    message.leader_group_index]:
                self.send(leader, LeaderInfoRequestBatcher())
        elif isinstance(message, LeaderInfoReplyBatcher):
            if message.round > self.rounds[message.leader_group_index]:
                self.rounds[message.leader_group_index] = message.round
            still_pending = []
            for group, batch in self.pending_resend_batches:
                if group == message.leader_group_index:
                    self.send(self._group_leader(group), batch)
                else:
                    still_pending.append((group, batch))
            self.pending_resend_batches = still_pending
        else:
            self.logger.fatal(f"unexpected batcher message {message!r}")


@dataclasses.dataclass
class _Phase1:
    # One dict per acceptor group of this leader group.
    phase1bs: list[dict[int, Phase1b]]
    pending_batches: list[ClientRequestBatch]
    # Slot to force-recover through phase 1, or -1 (Leader.scala:160-172).
    recover_slot: int
    resend_phase1as: object


class MenciusLeader(Actor):
    """(mencius/Leader.scala:130-870)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig,
                 resend_phase1as_period_s: float = 5.0,
                 send_high_watermark_every_n: int = 100,
                 send_noop_range_if_lagging_by: int = 100,
                 election_options: ElectionOptions = ElectionOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.send_high_watermark_every_n = send_high_watermark_every_n
        self.send_noop_range_if_lagging_by = send_noop_range_if_lagging_by
        self.resend_phase1as_period_s = resend_phase1as_period_s
        self.group_index = next(
            g for g, group in enumerate(config.leader_addresses)
            if address in group)
        self.index = list(
            config.leader_addresses[self.group_index]).index(address)
        self.round_system = ClassicRoundRobin(
            len(config.leader_addresses[self.group_index]))
        # Which leader group owns which slot (Leader.scala:208-213).
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        self.round = self.round_system.next_classic_round(0, -1)
        self.next_slot = self.group_index
        self.high_watermark = self.next_slot
        self.chosen_watermark = 0
        self._commands_since_watermark_send = 0
        self._current_proxy_leader = self.rng.randrange(
            config.num_proxy_leaders)

        self.election = ElectionParticipant(
            config.leader_election_addresses[self.group_index][self.index],
            transport, logger,
            config.leader_election_addresses[self.group_index],
            initial_leader_index=0, options=election_options, seed=seed)
        self.election.register(
            lambda leader_index: self.leader_change(
                leader_index == self.index, recover_slot=-1))

        self.state: object = ("inactive",)
        if self.index == 0:
            self.state = self._start_phase1(self.round,
                                            self.chosen_watermark, -1)

    # --- helpers ----------------------------------------------------------
    @property
    def _my_acceptor_groups(self) -> tuple:
        return self.config.acceptor_addresses[self.group_index]

    def _acceptor_group_index_by_slot(self, slot: int) -> int:
        self.logger.check_eq(self.slot_system.leader(slot), self.group_index)
        return ((slot // self.config.num_leader_groups)
                % len(self._my_acceptor_groups))

    def _proxy_leader(self) -> Address:
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_leader_addresses[
                self._current_proxy_leader]
        return self.config.proxy_leader_addresses[self.group_index]

    def _advance_proxy_leader(self) -> None:
        self._current_proxy_leader = (
            (self._current_proxy_leader + 1) % self.config.num_proxy_leaders)

    @staticmethod
    def _safe_value(phase1bs, slot: int):
        best_round, best_value = -1, None
        for phase1b in phase1bs:
            for info in phase1b.info:
                if info.slot == slot and info.vote_round > best_round:
                    best_round, best_value = info.vote_round, info.vote_value
        return NOOP if best_value is None else best_value

    def _start_phase1(self, round: int, chosen_watermark: int,
                      recover_slot: int) -> _Phase1:
        phase1a = Phase1a(round=round, chosen_watermark=chosen_watermark)
        for group in self._my_acceptor_groups:
            for acceptor in self.rng.sample(list(group),
                                            self.config.quorum_size):
                self.send(acceptor, phase1a)

        def resend():
            for group in self._my_acceptor_groups:
                for acceptor in group:
                    self.send(acceptor, phase1a)
            timer.start()

        timer = self.timer("resendPhase1as", self.resend_phase1as_period_s,
                           resend)
        timer.start()
        return _Phase1(
            phase1bs=[{} for _ in self._my_acceptor_groups],
            pending_batches=[], recover_slot=recover_slot,
            resend_phase1as=timer)

    def leader_change(self, is_new_leader: bool, recover_slot: int) -> None:
        if isinstance(self.state, _Phase1):
            self.state.resend_phase1as.stop()
        if not is_new_leader:
            self.state = ("inactive",)
            return
        self.round = self.round_system.next_classic_round(self.index,
                                                          self.round)
        self.state = self._start_phase1(self.round, self.chosen_watermark,
                                        recover_slot)

    def _process_batch(self, batch: ClientRequestBatch) -> None:
        self.logger.check_eq(self.state, ("phase2",))
        self.send(self._proxy_leader(),
                  Phase2a(slot=self.next_slot, round=self.round,
                          value=batch.batch))
        self._advance_proxy_leader()
        self.next_slot += self.config.num_leader_groups
        self._gossip_watermark(1)

    def _gossip_watermark(self, commands: int) -> None:
        # Periodically gossip our nextSlot so laggards can skip
        # (Leader.scala:455-480). A k-command run counts k commands.
        self._commands_since_watermark_send += commands
        if (self._commands_since_watermark_send
                >= self.send_high_watermark_every_n):
            self.send(self._proxy_leader(),
                      HighWatermark(next_slot=self.next_slot))
            self._commands_since_watermark_send = 0

    def _process_request_array(self, array: ClientRequestArray) -> None:
        """A drain's worth of independent requests: assign each its own
        OWNED slot (next_slot, next_slot + G, ...) and propose the whole
        strided block as ONE Phase2aRun carrying the stride.

        Slots within one leader group also stripe over its acceptor
        groups ((slot // G) % num_acceptor_groups), so a strided run has
        a single acceptor audience only with one acceptor group; with
        more, fall back to per-slot proposals."""
        self.logger.check_eq(self.state, ("phase2",))
        if len(self._my_acceptor_groups) > 1:
            for command in array.commands:
                self._process_batch(
                    ClientRequestBatch(CommandBatch((command,))))
            return
        stride = self.config.num_leader_groups
        k = len(array.commands)
        self.send(self._proxy_leader(), Phase2aRun(
            start_slot=self.next_slot, stride=stride, round=self.round,
            values=tuple(CommandBatch((c,)) for c in array.commands)))
        self._advance_proxy_leader()
        self.next_slot += k * stride
        self._gossip_watermark(k)

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1b):
            self._handle_phase1b(src, message)
        elif isinstance(message, ClientRequest):
            self._handle_client_request_batch(
                src, ClientRequestBatch(CommandBatch((message.command,))),
                from_client=True)
        elif isinstance(message, ClientRequestArray):
            self._handle_client_request_array(src, message)
        elif isinstance(message, ClientRequestBatch):
            self._handle_client_request_batch(src, message,
                                              from_client=False)
        elif isinstance(message, HighWatermark):
            self._handle_high_watermark(src, message)
        elif isinstance(message, LeaderInfoRequestClient):
            if self.state != ("inactive",):
                self.send(src, LeaderInfoReplyClient(self.group_index,
                                                     self.round))
        elif isinstance(message, LeaderInfoRequestBatcher):
            if self.state != ("inactive",):
                self.send(src, LeaderInfoReplyBatcher(self.group_index,
                                                      self.round))
        elif isinstance(message, Nack):
            self._handle_nack(src, message)
        elif isinstance(message, ChosenWatermark):
            self.chosen_watermark = max(self.chosen_watermark, message.slot)
        elif isinstance(message, Recover):
            self._handle_recover(src, message)
        else:
            self.logger.fatal(f"unexpected leader message {message!r}")

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1):
            return
        phase1 = self.state
        if phase1b.round != self.round:
            self.logger.check_lt(phase1b.round, self.round)
            return
        phase1.phase1bs[phase1b.group_index][phase1b.acceptor_index] = phase1b
        if any(len(g) < self.config.quorum_size for g in phase1.phase1bs):
            return

        max_slot = max(
            (info.slot for group in phase1.phase1bs
             for p1b in group.values() for info in p1b.info),
            default=-1)
        max_slot = max(max_slot, phase1.recover_slot)
        self.logger.check(
            max_slot == -1
            or self.slot_system.leader(max_slot) == self.group_index)

        # Fill only the slots this group owns (Leader.scala:624-647).
        start = self.slot_system.next_classic_round(
            self.group_index, self.chosen_watermark - 1)
        for slot in range(start, max_slot + 1,
                          self.config.num_leader_groups):
            group = phase1.phase1bs[self._acceptor_group_index_by_slot(slot)]
            self.send(self._proxy_leader(),
                      Phase2a(slot=slot, round=self.round,
                              value=self._safe_value(group.values(), slot)))
        # next_slot must clear the chosen watermark as well as the
        # voted max: Phase1bs report nothing below the watermark (all
        # chosen -- e.g. a predecessor's ChosenNoopRange), so with no
        # votes above it this would re-propose a pending command into
        # an already-Noop-chosen slot -- a second chosen value (found
        # by the WAL chaos soak's partition + leader-churn schedules).
        # Chosen slots >= the watermark are covered by quorum
        # intersection: some Phase1b carries their vote.
        self.next_slot = self.slot_system.next_classic_round(
            self.group_index, max(max_slot, self.chosen_watermark - 1))
        phase1.resend_phase1as.stop()
        self.state = ("phase2",)
        for batch in phase1.pending_batches:
            self._process_batch(batch)

    def _handle_client_request_batch(self, src: Address,
                                     batch: ClientRequestBatch,
                                     from_client: bool) -> None:
        if self.state == ("inactive",):
            if from_client:
                self.send(src, NotLeaderClient(self.group_index))
            else:
                self.send(src, NotLeaderBatcher(self.group_index, batch))
        elif isinstance(self.state, _Phase1):
            self.state.pending_batches.append(batch)
        else:
            self._process_batch(batch)

    def _handle_client_request_array(self, src: Address,
                                     array: ClientRequestArray) -> None:
        """The client edge of the drain-granular run pipeline: every
        command gets its OWN owned slot (transport-level coalescing,
        not slot sharing -- see multipaxos ClientRequestArray)."""
        if not array.commands:
            return
        if self.state == ("inactive",):
            self.send(src, NotLeaderClient(self.group_index))
        elif isinstance(self.state, _Phase1):
            for command in array.commands:
                self.state.pending_batches.append(
                    ClientRequestBatch(CommandBatch((command,))))
        else:
            self._process_request_array(array)

    def _handle_high_watermark(self, src: Address,
                               message: HighWatermark) -> None:
        """Skip our slots if we're lagging (Leader.scala:717-770)."""
        self.high_watermark = max(self.next_slot, self.high_watermark)
        if message.next_slot <= self.high_watermark:
            return
        self.high_watermark = message.next_slot
        if self.state != ("phase2",):
            return
        if self.high_watermark - self.next_slot \
                < self.send_noop_range_if_lagging_by:
            return
        end = self.slot_system.next_classic_round(self.group_index,
                                                  self.high_watermark)
        self.send(self._proxy_leader(),
                  Phase2aNoopRange(slot_start_inclusive=self.next_slot,
                                   slot_end_exclusive=end,
                                   round=self.round))
        self.next_slot = end

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        if nack.round <= self.round:
            return
        if self.state == ("inactive",):
            self.round = nack.round
        else:
            self.round = self.round_system.next_classic_round(self.index,
                                                              nack.round)
            self.leader_change(is_new_leader=True, recover_slot=-1)

    def _handle_recover(self, src: Address, recover: Recover) -> None:
        # A hole in one group's slots can only be fixed by that group
        # (Leader.scala:845-869); recover_slot threads through phase 1.
        if self.slot_system.leader(recover.slot) != self.group_index:
            return
        if self.state != ("inactive",):
            self.leader_change(is_new_leader=True,
                               recover_slot=recover.slot)


class MenciusProxyLeader(Actor):
    """(mencius/ProxyLeader.scala:31-420)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        # (start, end, round) -> pending state; None once Done.
        self.states: dict[tuple, object] = {}
        # Pending strided Phase2aRuns: start -> [round, stride, values,
        # acks set]. One O(1) record per run; round-monotone (a
        # same-start higher-round run evicts its predecessor).
        self._runs: dict[int, list] = {}
        # Retired / evicted run rounds: start -> set of rounds, for the
        # stray-ack check.
        self._done_runs: dict[int, set] = {}

    def _acceptor_group_index_by_slot(self, leader_group: int,
                                      slot: int) -> int:
        return ((slot // self.config.num_leader_groups)
                % len(self.config.acceptor_addresses[leader_group]))

    def receive(self, src: Address, message) -> None:
        if isinstance(message, HighWatermark):
            # Relay to every leader of every group
            # (ProxyLeader.scala:207-214).
            for leader in self.config.all_leaders():
                self.send(leader, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        elif isinstance(message, Phase2b):
            self._handle_phase2b(src, message)
        elif isinstance(message, Phase2aRun):
            self._handle_phase2a_run(src, message)
        elif isinstance(message, Phase2bRun):
            self._handle_phase2b_run(src, message)
        elif isinstance(message, Phase2aNoopRange):
            self._handle_phase2a_noop_range(src, message)
        elif isinstance(message, Phase2bNoopRange):
            self._handle_phase2b_noop_range(src, message)
        else:
            self.logger.fatal(f"unexpected proxy leader message {message!r}")

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        key = (phase2a.slot, phase2a.slot + 1, phase2a.round)
        if key in self.states:
            return
        leader_group = self.slot_system.leader(phase2a.slot)
        group = self.config.acceptor_addresses[leader_group][
            self._acceptor_group_index_by_slot(leader_group, phase2a.slot)]
        for acceptor in self.rng.sample(list(group),
                                        self.config.quorum_size):
            self.send(acceptor, phase2a)
        self.states[key] = {"phase2a": phase2a, "phase2bs": {}}

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        key = (phase2b.slot, phase2b.slot + 1, phase2b.round)
        state = self.states.get(key)
        if key not in self.states:
            self.logger.fatal(f"Phase2b for unknown {key}")
        if state is None or "phase2a" not in state:
            return  # Done or a noop-range entry
        state["phase2bs"][phase2b.acceptor_index] = phase2b
        if len(state["phase2bs"]) < self.config.quorum_size:
            return
        for replica in self.config.replica_addresses:
            self.send(replica, Chosen(slot=phase2b.slot,
                                      value=state["phase2a"].value))
        self.states[key] = None  # Done

    def _handle_phase2a_run(self, src: Address, run: Phase2aRun) -> None:
        """One write quorum for the whole strided run (one thrifty f+1
        sample, one forwarded message per member, one O(1) record).
        Slots of a strided leader-group run all live in ONE acceptor
        group only when that group is alone; otherwise decompose to the
        per-slot path (the leader already avoids sending runs then)."""
        k = len(run.values)
        if k == 0:
            return
        leader_group = self.slot_system.leader(run.start_slot)
        if len(self.config.acceptor_addresses[leader_group]) > 1:
            for i, value in enumerate(run.values):
                self._handle_phase2a(src, Phase2a(
                    slot=run.start_slot + i * run.stride,
                    round=run.round, value=value))
            return
        pending = self._runs.get(run.start_slot)
        if pending is not None:
            if run.round <= pending[0]:
                return  # duplicate (same or stale round)
            # Round-monotone eviction, mirroring the acceptor: the
            # higher-round re-proposal wins; remember the evicted round
            # so its straggler acks are recognized.
            self._done_runs.setdefault(run.start_slot,
                                       set()).add(pending[0])
        group = self.config.acceptor_addresses[leader_group][0]
        for acceptor in self.rng.sample(list(group),
                                        self.config.quorum_size):
            self.send(acceptor, run)  # encode the values ONCE
        self._runs[run.start_slot] = [run.round, run.stride,
                                      run.values, set()]

    def _handle_phase2b_run(self, src: Address,
                            phase2b: Phase2bRun) -> None:
        """Acceptors vote runs atomically, so quorum tracking is
        run-granular: count distinct acceptors, emit ONE ChosenRun per
        replica when f+1 acked."""
        run = self._runs.get(phase2b.start_slot)
        if run is None or run[0] != phase2b.round:
            if phase2b.round in self._done_runs.get(phase2b.start_slot,
                                                    ()):
                return  # straggler ack of a retired/evicted run
            if run is None:
                self.logger.fatal(
                    f"Phase2bRun for unknown run at {phase2b.start_slot}")
            return  # stale-round ack of a live re-proposed run
        round, stride, values, acks = run
        acks.add(phase2b.acceptor_index)
        if len(acks) < self.config.quorum_size:
            return
        for replica in self.config.replica_addresses:
            self.send(replica, ChosenRun(start_slot=phase2b.start_slot,
                                         stride=stride, values=values))
        del self._runs[phase2b.start_slot]
        self._done_runs.setdefault(phase2b.start_slot, set()).add(round)

    def _handle_phase2a_noop_range(self, src: Address,
                                   phase2a: Phase2aNoopRange) -> None:
        key = (phase2a.slot_start_inclusive, phase2a.slot_end_exclusive,
               phase2a.round)
        if key in self.states:
            return
        leader_group = self.slot_system.leader(phase2a.slot_start_inclusive)
        for group in self.config.acceptor_addresses[leader_group]:
            for acceptor in self.rng.sample(list(group),
                                            self.config.quorum_size):
                self.send(acceptor, phase2a)
        self.states[key] = {
            "noop_range": phase2a,
            "phase2bs_per_group": [
                {} for _ in self.config.acceptor_addresses[leader_group]],
        }

    def _handle_phase2b_noop_range(self, src: Address,
                                   phase2b: Phase2bNoopRange) -> None:
        key = (phase2b.slot_start_inclusive, phase2b.slot_end_exclusive,
               phase2b.round)
        state = self.states.get(key)
        if key not in self.states:
            self.logger.fatal(f"Phase2bNoopRange for unknown {key}")
        if state is None or "noop_range" not in state:
            return
        state["phase2bs_per_group"][phase2b.acceptor_group_index][
            phase2b.acceptor_index] = phase2b
        if any(len(g) < self.config.quorum_size
               for g in state["phase2bs_per_group"]):
            return
        for replica in self.config.replica_addresses:
            self.send(replica, ChosenNoopRange(
                slot_start_inclusive=phase2b.slot_start_inclusive,
                slot_end_exclusive=phase2b.slot_end_exclusive))
        self.states[key] = None  # Done


@dataclasses.dataclass
class _VoteState:
    vote_round: int
    vote_value: object


class MenciusAcceptor(Actor, DurableRole):
    """(mencius/Acceptor.scala:103-300)."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig, wal=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.leader_group_index, self.acceptor_group_index, self.index = next(
            (lg, ag, i)
            for lg, groups in enumerate(config.acceptor_addresses)
            for ag, group in enumerate(groups)
            for i, a in enumerate(group)
            if a == address)
        self.round_system = ClassicRoundRobin(
            len(config.leader_addresses[self.leader_group_index]))
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        self.round = -1
        self.states: SortedDict = SortedDict()
        # Run-voted state (Phase2aRun): start -> (count, stride, round,
        # values) -- one O(1) record per strided run. A slot's
        # authoritative vote is the HIGHEST round across both stores
        # (see _voted_info); the acceptor's monotone ``round`` makes
        # max-round resolution exact.
        self._voted_runs: SortedDict = SortedDict()
        self.max_voted_slot = -1
        # Durability (wal/): the multipaxos acceptor's group-commit
        # contract, strided -- promises/votes/runs/noop-ranges append
        # to the WAL and every dependent ack holds back until
        # on_drain's single fsync releases it (DurableRole).
        self._wal_init(wal)
        if wal is not None:
            self._recover_from_wal()

    # --- durability -------------------------------------------------------
    def _recover_from_wal(self) -> None:
        for record in self.wal.recover(self.logger):
            if isinstance(record, WalSnapshot):
                self.round = -1
                self.states.clear()
                self._voted_runs.clear()
                self.max_voted_slot = -1
            elif isinstance(record, WalPromise):
                self.round = max(self.round, record.round)
            elif isinstance(record, WalVote):
                self.round = max(self.round, record.round)
                self.states[record.slot] = _VoteState(
                    record.round, decode_value(record.value))
                self.max_voted_slot = max(self.max_voted_slot,
                                          record.slot)
            elif isinstance(record, WalVoteRun):
                self.round = max(self.round, record.round)
                self._store_run(record.start_slot, record.stride,
                                record.round,
                                decode_value_array(record.values))
            elif isinstance(record, WalNoopRange):
                self.round = max(self.round, record.round)
                self._store_noop_range(record.slot_start_inclusive,
                                       record.slot_end_exclusive,
                                       record.round)
            else:
                self.logger.fatal(
                    f"unexpected acceptor WAL record {record!r}")

    def _wal_compact(self) -> None:
        records = [WalPromise(round=self.round)]
        for start, (count, stride, rnd, values) in \
                self._voted_runs.items():
            records.append(WalVoteRun(
                start_slot=start, stride=stride, round=rnd,
                values=encode_value_array(values)))
        for slot, vs in self.states.items():
            records.append(WalVote(
                slot=slot, round=vs.vote_round,
                value=encode_value(vs.vote_value)))
        self.wal.compact(WalSnapshot(payload=b""), records)

    def on_drain(self) -> None:
        self._wal_drain()  # group commit, then release the held acks

    def _nack_leader(self, round: int, slot: int) -> Address:
        return self.config.leader_addresses[self.slot_system.leader(slot)][
            self.round_system.leader(round)]

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self._handle_phase2a(src, message)
        elif isinstance(message, Phase2aRun):
            self._handle_phase2a_run(src, message)
        elif isinstance(message, Phase2aNoopRange):
            self._handle_phase2a_noop_range(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round < self.round:
            self.send(src, Nack(round=self.round))
            return
        if self.wal is not None and phase1a.round > self.round:
            self.wal.append(WalPromise(round=phase1a.round))
        self.round = phase1a.round
        self._wal_send(src, Phase1b(group_index=self.acceptor_group_index,
                                    acceptor_index=self.index,
                                    round=self.round,
                                    info=self._voted_info(
                                        phase1a.chosen_watermark)))

    def _voted_info(self, minimum: int) -> tuple:
        """Every voted slot >= ``minimum`` with its HIGHEST-round vote,
        merging the per-slot store and the strided run store (a
        failover that ignored run votes would recover Noop over
        accepted values -- data loss). Recovery-only cold path: runs
        expand per slot here and nowhere else."""
        best: dict[int, tuple] = {
            slot: (self.states[slot].vote_round,
                   self.states[slot].vote_value)
            for slot in self.states.irange(minimum=minimum)}
        for start, (count, stride, rnd, values) in \
                self._voted_runs.items():
            if start + (count - 1) * stride < minimum:
                continue
            for i in range(count):
                slot = start + i * stride
                if slot < minimum:
                    continue
                cur = best.get(slot)
                if cur is None or rnd > cur[0]:
                    best[slot] = (rnd, values[i])
        return tuple(
            Phase1bSlotInfo(slot=slot, vote_round=rnd, vote_value=value)
            for slot, (rnd, value) in sorted(best.items()))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            self.send(self._nack_leader(phase2a.round, phase2a.slot),
                      Nack(round=self.round))
            return
        self.round = phase2a.round
        self.states[phase2a.slot] = _VoteState(self.round, phase2a.value)
        self.max_voted_slot = max(self.max_voted_slot, phase2a.slot)
        if self.wal is not None:
            self.wal.append(WalVote(
                slot=phase2a.slot, round=self.round,
                value=encode_value(phase2a.value)))
        self._wal_send(src, Phase2b(group_index=self.acceptor_group_index,
                                    acceptor_index=self.index,
                                    slot=phase2a.slot, round=self.round))

    def _handle_phase2a_run(self, src: Address, run: Phase2aRun) -> None:
        """A whole strided proposal run in one O(1) update: one round
        check, one run record, one Phase2bRun ack -- the per-drain
        shape of the per-slot _handle_phase2a."""
        if run.round < self.round:
            self.send(self._nack_leader(run.round, run.start_slot),
                      Nack(round=self.round))
            return
        self.round = run.round
        count = self._store_run(run.start_slot, run.stride, run.round,
                                run.values)
        if self.wal is not None:
            # A raw copy of the inbound lazy value segment, never a
            # re-materialization.
            self.wal.append(WalVoteRun(
                start_slot=run.start_slot, stride=run.stride,
                round=run.round,
                values=encode_value_array(run.values)))
        self._wal_send(src, Phase2bRun(
            acceptor_group_index=self.acceptor_group_index,
            acceptor_index=self.index, start_slot=run.start_slot,
            count=count, stride=run.stride, round=run.round))

    def _store_run(self, start_slot: int, stride: int, round: int,
                   values) -> int:
        """Merge one strided voted run into the run store; returns the
        run's count. Shared by the live Phase2aRun handler and WAL
        replay so truncation-tail semantics cannot drift."""
        count = len(values)
        old = self._voted_runs.get(start_slot)
        self._voted_runs[start_slot] = (count, stride, round, values)
        if old is not None and old[1] == stride and old[0] > count:
            # Same-start truncation (the multipaxos acceptor's tail
            # fix, strided): reinsert the longer predecessor's
            # non-overlapped voted tail so Phase1 recovery keeps it.
            old_count, old_stride, old_round, old_values = old
            tail_start = start_slot + count * stride
            if self._voted_runs.get(tail_start) is None:
                self._voted_runs[tail_start] = (
                    old_count - count, stride, old_round,
                    old_values[count:])
            else:
                for i in range(count, old_count):
                    slot = start_slot + i * stride
                    cur = self.states.get(slot)
                    if cur is None or cur.vote_round < old_round:
                        self.states[slot] = _VoteState(old_round,
                                                       old_values[i])
        self.max_voted_slot = max(
            self.max_voted_slot,
            start_slot + (count - 1) * stride)
        return count

    def _handle_phase2a_noop_range(self, src: Address,
                                   phase2a: Phase2aNoopRange) -> None:
        """Vote noop for every slot in the range owned by this acceptor
        group (Acceptor.scala:237-293)."""
        if phase2a.round < self.round:
            self.send(self._nack_leader(phase2a.round,
                                        phase2a.slot_start_inclusive),
                      Nack(round=self.round))
            return
        self.round = phase2a.round
        self._store_noop_range(phase2a.slot_start_inclusive,
                               phase2a.slot_end_exclusive, self.round)
        if self.wal is not None:
            # One O(1) record for the whole range; replay re-derives
            # the owned slots from the (restart-stable) config.
            self.wal.append(WalNoopRange(
                slot_start_inclusive=phase2a.slot_start_inclusive,
                slot_end_exclusive=phase2a.slot_end_exclusive,
                round=self.round))
        self._wal_send(src, Phase2bNoopRange(
            acceptor_group_index=self.acceptor_group_index,
            acceptor_index=self.index,
            slot_start_inclusive=phase2a.slot_start_inclusive,
            slot_end_exclusive=phase2a.slot_end_exclusive,
            round=self.round))

    def _store_noop_range(self, start_inclusive: int, end_exclusive: int,
                          round: int) -> None:
        """Vote noop for every slot this acceptor group owns in the
        range. Shared by the live handler and WAL replay."""
        num_groups = len(
            self.config.acceptor_addresses[self.leader_group_index])
        stride = self.config.num_leader_groups * num_groups
        start = start_inclusive
        while (start < end_exclusive
               and ((start // self.config.num_leader_groups) % num_groups)
               != self.acceptor_group_index):
            start += self.config.num_leader_groups
        for slot in range(start, end_exclusive, stride):
            self.states[slot] = _VoteState(round, NOOP)
