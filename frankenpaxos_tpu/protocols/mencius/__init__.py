"""Compartmentalized Mencius: multi-leader log partitioning.

Reference behavior: mencius/ (~3,000 LoC Scala; SURVEY.md section 2.2).
Leader groups own round-robin slot stripes; laggards skip their stripes
with noop ranges driven by high-watermark gossip. The slot-stripe layout
is the direct analog of sharding the slot axis across cores
(SURVEY.md section 2.3 item 4).
"""

from frankenpaxos_tpu.protocols.mencius.common import (
    DistributionScheme,
    MenciusConfig,
)
from frankenpaxos_tpu.protocols.mencius.replica import (
    MenciusClient,
    MenciusProxyReplica,
    MenciusReplica,
)
from frankenpaxos_tpu.protocols.mencius.roles import (
    MenciusAcceptor,
    MenciusBatcher,
    MenciusLeader,
    MenciusProxyLeader,
)

__all__ = [
    "DistributionScheme",
    "MenciusAcceptor",
    "MenciusBatcher",
    "MenciusClient",
    "MenciusConfig",
    "MenciusLeader",
    "MenciusProxyLeader",
    "MenciusProxyReplica",
    "MenciusReplica",
]
