"""Compartmentalized Mencius: multi-leader log partitioning.

Reference behavior: mencius/ (~3,000 LoC Scala; SURVEY.md section 2.2).
Leader groups own round-robin slot stripes; laggards skip their stripes
with noop ranges driven by high-watermark gossip. The slot-stripe layout
is the direct analog of sharding the slot axis across cores
(SURVEY.md section 2.3 item 4).

Hot-path structure: beside the reference's per-message shape, the
drain-granular run pipeline (docs/RUN_PIPELINE.md) ships one STRIDED
``Phase2aRun``/``Phase2bRun``/``ChosenRun`` per event-loop drain --
runs carry the owner's slot stride, so the ownership gaps between
consecutive owned slots stay implicit and idle groups' slots keep
coalescing into the noop-range skip machinery.
"""

# The ingest plane's run-descriptor codecs (mencius leaders consume
# IngestRun too; an unregistered descriptor would silently pickle).
from frankenpaxos_tpu.ingest import wire as _ingest_wire  # noqa: F401
# Importing registers the Mencius-specific binary codecs with the
# hybrid serializer (the inner MultiPaxos machinery's types are
# registered by protocols.multipaxos).
from frankenpaxos_tpu.protocols.mencius import wire  # noqa: F401
from frankenpaxos_tpu.protocols.mencius.common import (
    DistributionScheme,
    MenciusConfig,
)
from frankenpaxos_tpu.protocols.mencius.replica import (
    MenciusClient,
    MenciusProxyReplica,
    MenciusReplica,
)
from frankenpaxos_tpu.protocols.mencius.roles import (
    MenciusAcceptor,
    MenciusBatcher,
    MenciusLeader,
    MenciusProxyLeader,
)


__all__ = [
    "DistributionScheme",
    "MenciusAcceptor",
    "MenciusBatcher",
    "MenciusClient",
    "MenciusConfig",
    "MenciusLeader",
    "MenciusProxyLeader",
    "MenciusProxyReplica",
    "MenciusReplica",
]
