"""Mencius Replica, ProxyReplica, and Client.

Reference behavior: mencius/Replica.scala:151-560 (BufferMap log,
Chosen + ChosenNoopRange, in-order executeLog, recover timer on holes),
mencius/ProxyReplica.scala, mencius/Client.scala (per-leader-group round
tracking).
"""

from __future__ import annotations

import dataclasses
import random
import struct
from typing import Callable, Optional

from frankenpaxos_tpu.protocols.mencius.common import (
    Chosen,
    ChosenNoopRange,
    ChosenRun,
    ChosenWatermark,
    ClientReply,
    ClientReplyArray,
    ClientReplyBatch,
    ClientRequest,
    ClientRequestArray,
    Command,
    CommandBatch,
    CommandId,
    DistributionScheme,
    LeaderInfoReplyClient,
    LeaderInfoRequestClient,
    MenciusConfig,
    Noop,
    NotLeaderClient,
    Recover,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
    decode_value_array,
    encode_value_array,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runs.client import RetryAdmissionMixin, StagedWriteMixin
from frankenpaxos_tpu.runs.records import log_chosen_values, wal_log_chosen_run
from frankenpaxos_tpu.runs.routing import (
    pick_array_destination,
    pick_request_destination,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.serve.messages import Rejected
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap
from frankenpaxos_tpu.wal import (
    DurableRole,
    WalChosenRun,
    WalNoopRange,
    WalSnapshot,
)


class MenciusReplica(Actor, DurableRole):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, state_machine: StateMachine,
                 config: MenciusConfig, log_grow_size: int = 5000,
                 send_chosen_watermark_every_n: int = 100,
                 recover_min_period_s: float = 5.0,
                 recover_max_period_s: float = 10.0,
                 unsafe_dont_recover: bool = False, seed: int = 0,
                 wal=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.send_chosen_watermark_every_n = send_chosen_watermark_every_n
        self.index = list(config.replica_addresses).index(address)
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)
        self.log_grow_size = log_grow_size
        self.log: BufferMap = BufferMap(log_grow_size)
        self.executed_watermark = 0
        self._wm_dirty = False  # executed advanced since last drain
        self.num_chosen = 0
        self.high_watermark = -1
        self.client_table: dict[tuple, tuple[int, bytes]] = {}
        self.recovering_slot: Optional[int] = None
        # Durability (wal/): the multipaxos replica's group-commit
        # contract, strided (see protocols/multipaxos/replica.py).
        self._wal_init(wal)
        self.recover_timer = None
        if wal is not None:
            self._recover_from_wal()
        if not unsafe_dont_recover:
            self.recover_timer = self.timer(
                "recover",
                self.rng.uniform(recover_min_period_s, recover_max_period_s),
                self._recover)
            if wal is not None and self.executed_watermark < self.num_chosen:
                self.recovering_slot = self.executed_watermark
                self.recover_timer.start()

    # --- durability -------------------------------------------------------
    def _snapshot_payload(self) -> bytes:
        out = bytearray()
        out += struct.pack("<qq", self.executed_watermark,
                           self.high_watermark)
        _put_bytes(out, self.state_machine.to_bytes())
        out += struct.pack("<i", len(self.client_table))
        for (address, pseudonym), (client_id, result) in \
                self.client_table.items():
            _put_address(out, address)
            out += struct.pack("<qq", pseudonym, client_id)
            _put_bytes(out, result)
        return bytes(out)

    def _restore_snapshot(self, payload: bytes) -> None:
        watermark, high = struct.unpack_from("<qq", payload, 0)
        sm_bytes, at = _take_bytes(payload, 16)
        (n,) = struct.unpack_from("<i", payload, at)
        at += 4
        table: dict = {}
        for _ in range(n):
            address, at = _take_address(payload, at)
            pseudonym, client_id = struct.unpack_from("<qq", payload, at)
            result, at = _take_bytes(payload, at + 16)
            table[(address, pseudonym)] = (client_id, result)
        self.state_machine.from_bytes(sm_bytes)
        self.executed_watermark = watermark
        self.num_chosen = watermark
        self.high_watermark = high
        self.client_table = table
        self.log.garbage_collect(watermark)

    def _recover_from_wal(self) -> None:
        for record in self.wal.recover(self.logger):
            if isinstance(record, WalSnapshot):
                self.log = BufferMap(self.log_grow_size)
                self.executed_watermark = 0
                self.num_chosen = 0
                self.high_watermark = -1
                self.client_table = {}
                self._restore_snapshot(record.payload)
            elif isinstance(record, WalChosenRun):
                self._log_chosen(
                    record.start_slot, record.stride,
                    decode_value_array(record.values))
            elif isinstance(record, WalNoopRange):
                self._log_noop_range(record.slot_start_inclusive,
                                     record.slot_end_exclusive)
            else:
                self.logger.fatal(
                    f"unexpected replica WAL record {record!r}")
        self._execute_log()  # replies discarded; clients resend

    def _log_chosen(self, start_slot: int, stride: int, values) -> int:
        """Put a strided run of chosen values into the log
        (runs/records.py); returns how many were new. Shared by the
        live handlers and WAL replay."""
        new, high = log_chosen_values(self.log, self.executed_watermark,
                                      start_slot, stride, values)
        if high >= 0:
            self.high_watermark = max(self.high_watermark, high)
        self.num_chosen += new
        return new

    def _log_noop_range(self, start_inclusive: int,
                        end_exclusive: int) -> int:
        new = 0
        for slot in range(start_inclusive, end_exclusive,
                          self.config.num_leader_groups):
            if slot >= self.executed_watermark \
                    and self.log.get(slot) is None:
                self.log.put(slot, Noop())
                new += 1
        self.num_chosen += new
        return new

    def _wal_compact(self) -> None:
        records = []
        for slot, value in self.log.items(start=self.executed_watermark):
            records.append(WalChosenRun(
                start_slot=slot, stride=1,
                values=encode_value_array((value,))))
        self.wal.compact(WalSnapshot(payload=self._snapshot_payload()),
                         records)
        self.log.garbage_collect(self.executed_watermark)

    def on_drain(self) -> None:
        # Drain-granular watermark tail (paxload; see the multipaxos
        # replica): without it, a quiet pipeline leaves the leaders'
        # watermark view up to N-1 slots stale and a watermark-tied
        # admission budget wedges shut.
        if (self._wm_dirty
                and self.executed_watermark
                % self.send_chosen_watermark_every_n
                and self.executed_watermark % self.config.num_replicas
                == self.index):
            self._send_chosen_watermark()
        self._wm_dirty = False
        self._wal_drain()  # group commit, then release the held replies

    def _send_chosen_watermark(self) -> None:
        watermark = ChosenWatermark(slot=self.executed_watermark)
        proxy = self._proxy_replica()
        if proxy is not None:
            self._wal_send(proxy, watermark)
        else:
            for group in self.config.leader_addresses:
                for leader in group:
                    self._wal_send(leader, watermark)

    def _proxy_replica(self) -> Optional[Address]:
        if not self.config.proxy_replica_addresses:
            return None
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_replica_addresses[
                self.rng.randrange(self.config.num_proxy_replicas)]
        return self.config.proxy_replica_addresses[
            self.index % self.config.num_proxy_replicas]

    def _send_to_owning_leaders(self, message, slot: int) -> None:
        proxy = self._proxy_replica()
        if proxy is not None:
            self.send(proxy, message)
            return
        for leader in self.config.leader_addresses[
                self.slot_system.leader(slot)]:
            self.send(leader, message)

    def _recover(self) -> None:
        self.send_recover(self.executed_watermark)
        self.recover_timer.start()

    def send_recover(self, slot: int) -> None:
        self._send_to_owning_leaders(Recover(slot=slot), slot)

    def _execute_command(self, slot: int, command: Command,
                         replies: list[ClientReply]) -> None:
        cid = command.command_id
        key = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(key)
        if cached is not None:
            largest_id, cached_result = cached
            if cid.client_id < largest_id:
                return
            if cid.client_id == largest_id:
                replies.append(ClientReply(cid, slot, cached_result))
                return
        result = self.state_machine.run(command.command)
        self.client_table[key] = (cid.client_id, result)
        if slot % self.config.num_replicas == self.index:
            replies.append(ClientReply(cid, slot, result))

    def _execute_log(self) -> list[ClientReply]:
        replies: list[ClientReply] = []
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return replies
            slot = self.executed_watermark
            if isinstance(value, CommandBatch):
                for command in value.commands:
                    self._execute_command(slot, command, replies)
            self.executed_watermark += 1
            self._wm_dirty = True
            every_n = self.send_chosen_watermark_every_n
            if (self.executed_watermark % every_n == 0
                    and (self.executed_watermark // every_n)
                    % self.config.num_replicas == self.index):
                self._send_chosen_watermark()

    def _after_choose(self, coalesce_replies: bool = False) -> None:
        replies = self._execute_log()
        if replies:
            proxy = self._proxy_replica()
            if proxy is not None:
                self._wal_send(proxy,
                               ClientReplyBatch(batch=tuple(replies)))
            elif coalesce_replies and len(replies) > 1:
                # Run-pipeline drains ship each client ONE reply array
                # instead of one ClientReply per command.
                by_client: dict = {}
                for r in replies:
                    cid = r.command_id
                    by_client.setdefault(cid.client_address, []).append(
                        (cid.client_pseudonym, cid.client_id, r.slot,
                         r.result))
                for address, entries in by_client.items():
                    self._wal_send(address,
                                   ClientReplyArray(entries=tuple(entries)))
            else:
                for reply in replies:
                    self._wal_send(reply.command_id.client_address, reply)
        # Hole-recovery timer management (Replica.scala:432-462).
        if self.recover_timer is None:
            return
        has_hole = self.num_chosen != self.executed_watermark
        if self.recovering_slot is None and has_hole:
            self.recovering_slot = self.executed_watermark
            self.recover_timer.start()
        elif self.recovering_slot is not None and has_hole:
            if self.recovering_slot != self.executed_watermark:
                self.recovering_slot = self.executed_watermark
                self.recover_timer.reset()
        elif self.recovering_slot is not None and not has_hole:
            self.recovering_slot = None
            self.recover_timer.stop()

    def receive(self, src: Address, message) -> None:
        if isinstance(message, Chosen):
            if self._log_chosen(message.slot, 1, (message.value,)) == 0:
                return
            if self.wal is not None:
                self.wal.append(WalChosenRun(
                    start_slot=message.slot, stride=1,
                    values=encode_value_array((message.value,))))
            self._after_choose()
        elif isinstance(message, ChosenRun):
            self._handle_chosen_run(message)
        elif isinstance(message, ChosenNoopRange):
            new = self._log_noop_range(message.slot_start_inclusive,
                                       message.slot_end_exclusive)
            if new and self.wal is not None:
                self.wal.append(WalNoopRange(
                    slot_start_inclusive=message.slot_start_inclusive,
                    slot_end_exclusive=message.slot_end_exclusive,
                    round=0))
            self._after_choose()
        else:
            self.logger.fatal(f"unexpected replica message {message!r}")

    def _handle_chosen_run(self, run: ChosenRun) -> None:
        """A strided drain of chosen values in one message: log the
        whole run, execute once, coalesce replies per client."""
        new = self._log_chosen(run.start_slot, run.stride, run.values)
        if new == 0:
            return
        if self.wal is not None:
            # Common case: every slot new -> one raw-copy segment
            # record; partial overlap falls back to per-new-slot
            # records (runs/records.py).
            wal_log_chosen_run(self.wal, self.log.get, run.start_slot,
                               run.stride, run.values,
                               all_new=(new == len(run.values)),
                               encode=encode_value_array)
        self._after_choose(coalesce_replies=True)


class MenciusProxyReplica(Actor):
    """(mencius/ProxyReplica.scala): unbatch replies; route watermarks to
    all leaders and Recovers to the owning group."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.slot_system = ClassicRoundRobin(config.num_leader_groups)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientReplyBatch):
            for reply in message.batch:
                self.send(reply.command_id.client_address, reply)
        elif isinstance(message, ChosenWatermark):
            for leader in self.config.all_leaders():
                self.send(leader, message)
        elif isinstance(message, Recover):
            for leader in self.config.leader_addresses[
                    self.slot_system.leader(message.slot)]:
                self.send(leader, message)
        else:
            self.logger.fatal(f"unexpected proxy replica message {message!r}")


@dataclasses.dataclass
class _PendingWrite:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object
    attempts: int = 0
    backoff_pending: bool = False


class MenciusClient(RetryAdmissionMixin, StagedWriteMixin, Actor):
    """(mencius/Client.scala): like the MultiPaxos client, but tracks a
    round per leader group and targets a random group per request."""

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MenciusConfig,
                 resend_period_s: float = 10.0,
                 coalesce_writes: bool = False, seed: int = 0,
                 retry_budget: int = 0, backoff=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        # runs/ retry discipline (serve/backoff.py): 0 = unlimited
        # resends, the pre-paxload behavior; see multipaxos
        # ClientOptions.retry_budget for the contract.
        from frankenpaxos_tpu.serve.backoff import Backoff

        self._retry_budget = retry_budget
        self._retry_backoff = backoff or Backoff()
        # Coalesce this event-loop pass's writes into ONE
        # ClientRequestArray to a random group's leader (each command
        # still gets its own owned slot there). Flushed by on_drain /
        # flush_writes (runs/client.py); resends still go per-request.
        self.coalesce_writes = coalesce_writes
        self.rounds = [0] * config.num_leader_groups
        self.ids: dict[int, int] = {}
        self.states: dict[int, _PendingWrite] = {}
        self._init_staging()
        # paxfan: consistent ring over the ingest-batcher tier (see
        # the multipaxos client) -- sessions pin to shards; timeouts
        # suspect one shard; Rejected floors backoff per shard.
        from frankenpaxos_tpu.runs.routing import make_fan_router

        self._fan = make_fan_router(config,
                                    revive_after_s=resend_period_s)

    def _random_group_leader(self) -> Address:
        group = self.rng.randrange(self.config.num_leader_groups)
        return self._leader_of_group(group)

    def _send_request(self, request: ClientRequest) -> None:
        # runs/routing ladder (ingest batchers, ring-pinned per
        # session > batchers > a random group's leader: any group can
        # sequence any command).
        dst = pick_request_destination(
            self.config, self.rng, self._random_group_leader,
            fan=self._fan,
            key=(self.address, request.command.command_id.client_pseudonym))
        self.send(dst, request)

    def _note_shed_source(self, src: Address, rejected) -> float:
        if self._fan is None:
            return 0.0
        from frankenpaxos_tpu.ingest.fan import shard_of_address

        shard = shard_of_address(self.config, src)
        if shard < 0:
            return 0.0
        self._fan.note_shed(shard, rejected.retry_after_ms)
        return self._fan.floor_delay_s(shard)

    def _leader_of_group(self, group: int) -> Address:
        rs = ClassicRoundRobin(len(self.config.leader_addresses[group]))
        return self.config.leader_addresses[group][
            rs.leader(self.rounds[group])]

    def _flush_staged(self, staged: list) -> None:
        """Ship writes staged by ``coalesce_writes`` as one array to a
        random leader group (any group can sequence any command); the
        array rides the client-scoped ring key (pseudonym -1)."""
        dst = pick_array_destination(self.config, self.rng,
                                     self._random_group_leader,
                                     fan=self._fan,
                                     key=(self.address, -1))
        self.send(dst, ClientRequestArray(commands=tuple(staged)))

    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.states:
            raise RuntimeError(
                f"pseudonym {pseudonym} already has a pending operation")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, id), command))
        if self.coalesce_writes:
            self._stage_write(request.command)
        else:
            self._send_request(request)

        def resend():
            state = self.states.get(pseudonym)
            if not isinstance(state, _PendingWrite) or state.id != id \
                    or not self._consume_retry(pseudonym, state,
                                               "failover"):
                return
            if self._fan is not None:
                # paxfan: suspect this key's shard so the resend
                # routes past it; other keys stay pinned.
                self._fan.suspect_key(self.address, pseudonym)
            self._send_request(request)
            timer.start()

        timer = self.timer(f"resendWrite{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.states[pseudonym] = _PendingWrite(
            id, command, callback or (lambda _: None), timer)
        self.ids[pseudonym] = id + 1

    # Rejected handling + backoff/reissue scheduling live in
    # RetryAdmissionMixin (runs/client.py); only the re-send is ours.
    def _reissue(self, pseudonym: int, state) -> None:
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, state.id), state.command))
        if self.coalesce_writes:
            # Coalesce backoff expiries back into one array instead of
            # a retry storm of singles.
            self._stage_write(request.command)
        else:
            self._send_request(request)

    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientReply):
            pseudonym = message.command_id.client_pseudonym
            state = self.states.get(pseudonym)
            if state is None or message.command_id.client_id != state.id:
                return
            state.resend.stop()
            del self.states[pseudonym]
            state.callback(message.result)
        elif isinstance(message, ClientReplyArray):
            # A replica's whole drain of replies to this client in one
            # message; per-entry resolution mirrors ClientReply.
            for pseudonym, client_id, _slot, result in message.entries:
                state = self.states.get(pseudonym)
                if state is None or client_id != state.id:
                    continue
                state.resend.stop()
                del self.states[pseudonym]
                state.callback(result)
        elif isinstance(message, NotLeaderClient):
            for leader in self.config.leader_addresses[
                    message.leader_group_index]:
                self.send(leader, LeaderInfoRequestClient())
        elif isinstance(message, Rejected):
            self._handle_rejected(src, message)
        elif isinstance(message, LeaderInfoReplyClient):
            if message.round > self.rounds[message.leader_group_index]:
                self.rounds[message.leader_group_index] = message.round
                for pseudonym, state in self.states.items():
                    self._send_request(ClientRequest(Command(
                        CommandId(self.address, pseudonym, state.id),
                        state.command)))
        else:
            self.logger.fatal(f"unexpected client message {message!r}")
