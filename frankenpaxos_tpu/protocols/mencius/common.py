"""Mencius messages and configuration.

Reference behavior: mencius/Mencius.proto and mencius/Config.scala.
Mencius partitions the log round-robin across *leader groups*; each
leader group runs its own MultiPaxos over its own acceptor groups.
Lagging groups skip their slots by choosing noop *ranges*
(Mencius.proto:160-202).
"""

from __future__ import annotations

import dataclasses
import enum

# Re-used value/message shapes identical to MultiPaxos. The
# transport-level coalescing envelopes (ClientRequestArray /
# ClientReplyArray) are shared too: their SoA codecs live in
# multipaxos/wire.py and carry no slot semantics, so the Mencius twist
# (strided slot ownership) never reaches them.
from frankenpaxos_tpu.protocols.multipaxos.messages import (  # noqa: F401
    ChosenWatermark,
    ClientReply,
    ClientReplyArray,
    ClientReplyBatch,
    ClientRequest,
    ClientRequestArray,
    ClientRequestBatch,
    Command,
    CommandBatch,
    CommandBatchOrNoop,
    CommandId,
    Nack,
    NOOP,
    Noop,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2b,
    Recover,
)
from frankenpaxos_tpu.runtime.transport import Address


class DistributionScheme(enum.Enum):
    HASH = "hash"
    COLOCATED = "colocated"


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class HighWatermark:
    next_slot: int


@dataclasses.dataclass(frozen=True)
class Phase2aNoopRange:
    slot_start_inclusive: int
    slot_end_exclusive: int
    round: int


@dataclasses.dataclass(frozen=True)
class Phase2bNoopRange:
    acceptor_group_index: int
    acceptor_index: int
    slot_start_inclusive: int
    slot_end_exclusive: int
    round: int


@dataclasses.dataclass(frozen=True)
class ChosenNoopRange:
    slot_start_inclusive: int
    slot_end_exclusive: int


# --- drain-granular run pipeline (the MultiPaxos
# ClientRequestArray -> Phase2aRun -> Phase2bRange -> ChosenRun redesign
# ported to Mencius' partitioned log). A Mencius leader group owns every
# G-th slot (G = num_leader_groups, the round-robin slot stride), so one
# drain's worth of commands occupies a STRIDED run
# ``start, start + stride, ..., start + (k-1) * stride`` -- the run
# messages carry the owner's stride so the ownership gaps between
# consecutive owned slots stay implicit (they belong to OTHER groups and
# coalesce into Phase2aNoopRange skip ranges when those groups lag)
# instead of materializing as per-slot noops.


@dataclasses.dataclass(frozen=True)
class Phase2aRun:
    """Phase2as for a strided slot run in one round, one message.

    ``values[i]`` is proposed at slot ``start_slot + i * stride``. The
    proposing leader group owns exactly those slots; one message per
    event-loop drain replaces one Phase2a per command
    (mencius/Leader.scala:331-408's per-slot processClientRequestBatch).
    """

    start_slot: int
    stride: int
    round: int
    values: tuple  # tuple[CommandBatchOrNoop, ...], one per owned slot


@dataclasses.dataclass(frozen=True)
class Phase2bRun:
    """One acceptor's votes for a whole strided Phase2aRun, one message.

    The acceptor votes a run atomically (one round check, one O(1) run
    record), so the ack is run-granular too: ``count`` slots starting at
    ``start_slot`` with step ``stride`` (the Mencius analog of the
    MultiPaxos Phase2bRange)."""

    acceptor_group_index: int
    acceptor_index: int
    start_slot: int
    count: int
    stride: int
    round: int


@dataclasses.dataclass(frozen=True)
class ChosenRun:
    """Chosen values for a strided slot run, one message per replica per
    drain (vs one Chosen per slot)."""

    start_slot: int
    stride: int
    values: tuple  # tuple[CommandBatchOrNoop, ...], one per owned slot


@dataclasses.dataclass(frozen=True)
class NotLeaderClient:
    leader_group_index: int


@dataclasses.dataclass(frozen=True)
class LeaderInfoRequestClient:
    pass


@dataclasses.dataclass(frozen=True)
class LeaderInfoReplyClient:
    leader_group_index: int
    round: int


@dataclasses.dataclass(frozen=True)
class NotLeaderBatcher:
    leader_group_index: int
    client_request_batch: ClientRequestBatch


@dataclasses.dataclass(frozen=True)
class LeaderInfoRequestBatcher:
    pass


@dataclasses.dataclass(frozen=True)
class LeaderInfoReplyBatcher:
    leader_group_index: int
    round: int


@dataclasses.dataclass(frozen=True)
class MenciusConfig:
    """(mencius/Config.scala:20-60):
    - 0 or >= f+1 batchers
    - >= 1 leader group, each of >= f+1 leaders (elections mirror them)
    - one set of >= 1 acceptor groups of 2f+1 per leader group
    - >= f+1 replicas; 0 or >= f+1 proxy replicas
    """

    f: int
    batcher_addresses: tuple
    leader_addresses: tuple          # [group][member]
    leader_election_addresses: tuple  # [group][member]
    proxy_leader_addresses: tuple
    acceptor_addresses: tuple        # [leader group][acceptor group][member]
    replica_addresses: tuple
    proxy_replica_addresses: tuple
    distribution_scheme: DistributionScheme = DistributionScheme.HASH
    # paxingest disseminators (ingest/, docs/TRANSPORT.md): any count
    # >= 1 is valid -- WAL-free, client retries cover failover.
    ingest_batcher_addresses: tuple = ()

    @property
    def num_ingest_batchers(self) -> int:
        return len(self.ingest_batcher_addresses)

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    @property
    def num_batchers(self) -> int:
        return len(self.batcher_addresses)

    @property
    def num_leader_groups(self) -> int:
        return len(self.leader_addresses)

    @property
    def num_proxy_leaders(self) -> int:
        return len(self.proxy_leader_addresses)

    @property
    def num_replicas(self) -> int:
        return len(self.replica_addresses)

    @property
    def num_proxy_replicas(self) -> int:
        return len(self.proxy_replica_addresses)

    def all_leaders(self) -> list[Address]:
        return [a for group in self.leader_addresses for a in group]

    def check_valid(self) -> None:
        def require(cond, msg):
            if not cond:
                raise ValueError(msg)

        require(self.f >= 1, "f must be >= 1")
        require(self.num_batchers == 0 or self.num_batchers >= self.f + 1,
                "num_batchers must be 0 or >= f+1")
        require(self.num_leader_groups >= 1, "need >= 1 leader group")
        for i, group in enumerate(self.leader_addresses):
            require(len(group) >= self.f + 1,
                    f"leader group {i} must have >= f+1 members")
        require(len(self.leader_election_addresses)
                == self.num_leader_groups,
                "election groups must mirror leader groups")
        require(self.num_proxy_leaders >= self.f + 1,
                "num_proxy_leaders must be >= f+1")
        require(len(self.acceptor_addresses) == self.num_leader_groups,
                "one acceptor-group set per leader group")
        for groups in self.acceptor_addresses:
            require(len(groups) >= 1, "need >= 1 acceptor group")
            for group in groups:
                require(len(group) == 2 * self.f + 1,
                        "acceptor groups must have 2f+1 members")
        require(self.num_replicas >= self.f + 1,
                "num_replicas must be >= f+1")
        require(self.num_proxy_replicas == 0
                or self.num_proxy_replicas >= self.f + 1,
                "num_proxy_replicas must be 0 or >= f+1")
