"""Binary codecs for Mencius' own hot-path messages.

Mencius reuses the MultiPaxos message types for its inner MultiPaxos
machinery (common.py re-exports them), so those already ride the
codecs in protocols/multipaxos/wire.py. This module covers the
Mencius-specific stream: per-slot Chosen, the HighWatermark gossip
(sent every command at LT settings), and the noop-range skip triplet
(Mencius.proto:160-202) -- all pure fixed-width layouts.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols.mencius.common import (
    Chosen,
    ChosenNoopRange,
    ChosenRun,
    HighWatermark,
    Phase2aNoopRange,
    Phase2aRun,
    Phase2bNoopRange,
    Phase2bRun,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_value,
    _put_value_array,
    _take_value,
    _take_value_array,
)
from frankenpaxos_tpu.runtime.serializer import (
    MessageCodec,
    register_codec,
)

_I64 = struct.Struct("<q")
_QQI = struct.Struct("<qqi")
_P2BNR = struct.Struct("<qqiiq")  # start, end, group, acceptor, round
_I64I64 = struct.Struct("<qq")


class MenciusChosenCodec(MessageCodec):
    message_type = Chosen
    tag = 8

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return Chosen(slot=slot, value=value), at


class HighWatermarkCodec(MessageCodec):
    message_type = HighWatermark
    tag = 9

    def encode(self, out, message):
        out += _I64.pack(message.next_slot)

    def decode(self, buf, at):
        (next_slot,) = _I64.unpack_from(buf, at)
        return HighWatermark(next_slot=next_slot), at + 8


class Phase2aNoopRangeCodec(MessageCodec):
    message_type = Phase2aNoopRange
    tag = 10

    def encode(self, out, message):
        out += _QQI.pack(message.slot_start_inclusive,
                         message.slot_end_exclusive, message.round)

    def decode(self, buf, at):
        start, end, round = _QQI.unpack_from(buf, at)
        return Phase2aNoopRange(slot_start_inclusive=start,
                                slot_end_exclusive=end,
                                round=round), at + 20


class Phase2bNoopRangeCodec(MessageCodec):
    message_type = Phase2bNoopRange
    tag = 11

    def encode(self, out, message):
        out += _P2BNR.pack(message.slot_start_inclusive,
                           message.slot_end_exclusive,
                           message.acceptor_group_index,
                           message.acceptor_index, message.round)

    def decode(self, buf, at):
        start, end, group, acceptor, round = _P2BNR.unpack_from(buf, at)
        return Phase2bNoopRange(acceptor_group_index=group,
                                acceptor_index=acceptor,
                                slot_start_inclusive=start,
                                slot_end_exclusive=end,
                                round=round), at + _P2BNR.size


class ChosenNoopRangeCodec(MessageCodec):
    message_type = ChosenNoopRange
    tag = 12

    def encode(self, out, message):
        out += _I64I64.pack(message.slot_start_inclusive,
                            message.slot_end_exclusive)

    def decode(self, buf, at):
        start, end = _I64I64.unpack_from(buf, at)
        return ChosenNoopRange(slot_start_inclusive=start,
                               slot_end_exclusive=end), at + 16


# --- strided run-pipeline codecs --------------------------------------------
# Fixed-layout SoA forms mirroring multipaxos/wire.py's run codecs: the
# value payload rides _put_value_array's address-table layout (decoding
# yields a LazyValueArray, so forwarding roles never materialize
# Command objects), prefixed by the run header carrying the owner's
# slot stride.

_QQI64 = struct.Struct("<qqq")  # start, stride, round


class MenciusPhase2aRunCodec(MessageCodec):
    message_type = Phase2aRun
    tag = 113

    def encode(self, out, message):
        out += _QQI64.pack(message.start_slot, message.stride,
                           message.round)
        _put_value_array(out, message.values)

    def decode(self, buf, at):
        start, stride, round = _QQI64.unpack_from(buf, at)
        values, at = _take_value_array(buf, at + 24)
        return Phase2aRun(start_slot=start, stride=stride, round=round,
                          values=values), at


_P2BRUN = struct.Struct("<qqqqii")  # start, count, stride, round, grp, acc


class MenciusPhase2bRunCodec(MessageCodec):
    message_type = Phase2bRun
    tag = 126

    def encode(self, out, message):
        out += _P2BRUN.pack(message.start_slot, message.count,
                            message.stride, message.round,
                            message.acceptor_group_index,
                            message.acceptor_index)

    def decode(self, buf, at):
        start, count, stride, round, group, acceptor = \
            _P2BRUN.unpack_from(buf, at)
        return Phase2bRun(acceptor_group_index=group,
                          acceptor_index=acceptor, start_slot=start,
                          count=count, stride=stride,
                          round=round), at + _P2BRUN.size


class MenciusChosenRunCodec(MessageCodec):
    message_type = ChosenRun
    tag = 127

    def encode(self, out, message):
        out += _I64I64.pack(message.start_slot, message.stride)
        _put_value_array(out, message.values)

    def decode(self, buf, at):
        start, stride = _I64I64.unpack_from(buf, at)
        values, at = _take_value_array(buf, at + 16)
        return ChosenRun(start_slot=start, stride=stride,
                         values=values), at


for _codec in (MenciusChosenCodec(), HighWatermarkCodec(),
               Phase2aNoopRangeCodec(), Phase2bNoopRangeCodec(),
               ChosenNoopRangeCodec(), MenciusPhase2aRunCodec(),
               MenciusPhase2bRunCodec(), MenciusChosenRunCodec()):
    register_codec(_codec)
