"""Binary codecs for Mencius' own hot-path messages.

Mencius reuses the MultiPaxos message types for its inner MultiPaxos
machinery (common.py re-exports them), so those already ride the
codecs in protocols/multipaxos/wire.py. This module covers the
Mencius-specific stream: per-slot Chosen, the HighWatermark gossip
(sent every command at LT settings), and the noop-range skip triplet
(Mencius.proto:160-202) -- all pure fixed-width layouts.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols.mencius.common import (
    Chosen,
    ChosenNoopRange,
    ChosenRun,
    HighWatermark,
    LeaderInfoReplyBatcher,
    LeaderInfoReplyClient,
    LeaderInfoRequestBatcher,
    LeaderInfoRequestClient,
    NotLeaderBatcher,
    NotLeaderClient,
    Phase2aNoopRange,
    Phase2aRun,
    Phase2bNoopRange,
    Phase2bRun,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import ClientRequestBatch
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _EmptyCodec,
    _put_value,
    _put_value_array,
    _take_value,
    _take_value_array,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I64 = struct.Struct("<q")
_QQI = struct.Struct("<qqi")
_P2BNR = struct.Struct("<qqiiq")  # start, end, group, acceptor, round
_I64I64 = struct.Struct("<qq")


class MenciusChosenCodec(MessageCodec):
    message_type = Chosen
    tag = 8

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return Chosen(slot=slot, value=value), at


class HighWatermarkCodec(MessageCodec):
    message_type = HighWatermark
    tag = 9

    def encode(self, out, message):
        out += _I64.pack(message.next_slot)

    def decode(self, buf, at):
        (next_slot,) = _I64.unpack_from(buf, at)
        return HighWatermark(next_slot=next_slot), at + 8


class Phase2aNoopRangeCodec(MessageCodec):
    message_type = Phase2aNoopRange
    tag = 10

    def encode(self, out, message):
        out += _QQI.pack(message.slot_start_inclusive,
                         message.slot_end_exclusive, message.round)

    def decode(self, buf, at):
        start, end, round = _QQI.unpack_from(buf, at)
        return Phase2aNoopRange(slot_start_inclusive=start,
                                slot_end_exclusive=end,
                                round=round), at + 20


class Phase2bNoopRangeCodec(MessageCodec):
    message_type = Phase2bNoopRange
    tag = 11

    def encode(self, out, message):
        out += _P2BNR.pack(message.slot_start_inclusive,
                           message.slot_end_exclusive,
                           message.acceptor_group_index,
                           message.acceptor_index, message.round)

    def decode(self, buf, at):
        start, end, group, acceptor, round = _P2BNR.unpack_from(buf, at)
        return Phase2bNoopRange(acceptor_group_index=group,
                                acceptor_index=acceptor,
                                slot_start_inclusive=start,
                                slot_end_exclusive=end,
                                round=round), at + _P2BNR.size


class ChosenNoopRangeCodec(MessageCodec):
    message_type = ChosenNoopRange
    tag = 12

    def encode(self, out, message):
        out += _I64I64.pack(message.slot_start_inclusive,
                            message.slot_end_exclusive)

    def decode(self, buf, at):
        start, end = _I64I64.unpack_from(buf, at)
        return ChosenNoopRange(slot_start_inclusive=start,
                               slot_end_exclusive=end), at + 16


# --- strided run-pipeline codecs --------------------------------------------
# Fixed-layout SoA forms mirroring multipaxos/wire.py's run codecs: the
# value payload rides _put_value_array's address-table layout (decoding
# yields a LazyValueArray, so forwarding roles never materialize
# Command objects), prefixed by the run header carrying the owner's
# slot stride.

_QQI64 = struct.Struct("<qqq")  # start, stride, round


class MenciusPhase2aRunCodec(MessageCodec):
    message_type = Phase2aRun
    tag = 113

    def encode(self, out, message):
        out += _QQI64.pack(message.start_slot, message.stride,
                           message.round)
        _put_value_array(out, message.values)

    def decode(self, buf, at):
        start, stride, round = _QQI64.unpack_from(buf, at)
        values, at = _take_value_array(buf, at + 24)
        return Phase2aRun(start_slot=start, stride=stride, round=round,
                          values=values), at


_P2BRUN = struct.Struct("<qqqqii")  # start, count, stride, round, grp, acc


class MenciusPhase2bRunCodec(MessageCodec):
    message_type = Phase2bRun
    tag = 126

    def encode(self, out, message):
        out += _P2BRUN.pack(message.start_slot, message.count,
                            message.stride, message.round,
                            message.acceptor_group_index,
                            message.acceptor_index)

    def decode(self, buf, at):
        start, count, stride, round, group, acceptor = \
            _P2BRUN.unpack_from(buf, at)
        return Phase2bRun(acceptor_group_index=group,
                          acceptor_index=acceptor, start_slot=start,
                          count=count, stride=stride,
                          round=round), at + _P2BRUN.size


class MenciusChosenRunCodec(MessageCodec):
    message_type = ChosenRun
    tag = 127

    def encode(self, out, message):
        out += _I64I64.pack(message.start_slot, message.stride)
        _put_value_array(out, message.values)

    def decode(self, buf, at):
        start, stride = _I64I64.unpack_from(buf, at)
        values, at = _take_value_array(buf, at + 16)
        return ChosenRun(start_slot=start, stride=stride,
                         values=values), at


# Leader-change client redirects on the extended tag page. Mencius's
# shapes carry the owning leader GROUP index on top of multipaxos's
# (tags 138-143); hot exactly during failover storms, when every
# queued client op resends at once (COD301 burn-down, paxflow PR).

_IQ = struct.Struct("<iq")


class MenciusNotLeaderClientCodec(MessageCodec):
    message_type = NotLeaderClient
    tag = 144

    def encode(self, out, message):
        out += _I64.pack(message.leader_group_index)

    def decode(self, buf, at):
        (group,) = _I64.unpack_from(buf, at)
        return NotLeaderClient(leader_group_index=group), at + 8


class MenciusLeaderInfoRequestClientCodec(_EmptyCodec):
    message_type = LeaderInfoRequestClient
    tag = 145


class MenciusLeaderInfoReplyClientCodec(MessageCodec):
    message_type = LeaderInfoReplyClient
    tag = 146

    def encode(self, out, message):
        out += _IQ.pack(message.leader_group_index, message.round)

    def decode(self, buf, at):
        group, round = _IQ.unpack_from(buf, at)
        return LeaderInfoReplyClient(leader_group_index=group,
                                     round=round), at + _IQ.size


class MenciusNotLeaderBatcherCodec(MessageCodec):
    message_type = NotLeaderBatcher
    tag = 147

    def encode(self, out, message):
        out += _I64.pack(message.leader_group_index)
        _put_value(out, message.client_request_batch.batch)

    def decode(self, buf, at):
        (group,) = _I64.unpack_from(buf, at)
        batch, at = _take_value(buf, at + 8)
        return NotLeaderBatcher(
            leader_group_index=group,
            client_request_batch=ClientRequestBatch(batch)), at


class MenciusLeaderInfoRequestBatcherCodec(_EmptyCodec):
    message_type = LeaderInfoRequestBatcher
    tag = 148


class MenciusLeaderInfoReplyBatcherCodec(MessageCodec):
    message_type = LeaderInfoReplyBatcher
    tag = 149

    def encode(self, out, message):
        out += _IQ.pack(message.leader_group_index, message.round)

    def decode(self, buf, at):
        group, round = _IQ.unpack_from(buf, at)
        return LeaderInfoReplyBatcher(leader_group_index=group,
                                      round=round), at + _IQ.size


for _codec in (MenciusChosenCodec(), HighWatermarkCodec(),
               Phase2aNoopRangeCodec(), Phase2bNoopRangeCodec(),
               ChosenNoopRangeCodec(), MenciusPhase2aRunCodec(),
               MenciusPhase2bRunCodec(), MenciusChosenRunCodec(),
               MenciusNotLeaderClientCodec(),
               MenciusLeaderInfoRequestClientCodec(),
               MenciusLeaderInfoReplyClientCodec(),
               MenciusNotLeaderBatcherCodec(),
               MenciusLeaderInfoRequestBatcherCodec(),
               MenciusLeaderInfoReplyBatcherCodec()):
    register_codec(_codec)
