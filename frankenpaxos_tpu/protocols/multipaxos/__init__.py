"""Compartmentalized MultiPaxos -- the flagship protocol.

Reference behavior: multipaxos/ (~4,300 LoC Scala; see SURVEY.md section
2.2). Roles: Batcher -> Leader -> ProxyLeader -> Acceptor (groups or
grid) -> ProxyLeader -> Replica -> ProxyReplica -> Client, plus
linearizable / sequential / eventual reads.

The Phase2b vote-collection loop (the reference's hottest code) runs on
a pluggable quorum tracker; the "tpu" backend batches votes onto the
TpuQuorumChecker vote board (ops/quorum.py) once per event-loop drain.
"""

from frankenpaxos_tpu.ingest import wire as _ingest_wire  # noqa: F401
# Importing registers the hot-path binary codecs with the hybrid
# serializer (its module docstring explains the wire schema) -- the
# protocol's own page plus the ingest plane's IngestRun/NotLeaderIngest
# descriptors (ingest/wire.py; an unregistered IngestRun would silently
# pickle, exactly the COD301 class).
from frankenpaxos_tpu.protocols.multipaxos import wire  # noqa: F401
from frankenpaxos_tpu.protocols.multipaxos.acceptor import (
    Acceptor,
    AcceptorOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.batcher import (
    Batcher,
    BatcherOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.client import Client, ClientOptions
from frankenpaxos_tpu.protocols.multipaxos.config import (
    DistributionScheme,
    MultiPaxosConfig,
)
from frankenpaxos_tpu.protocols.multipaxos.leader import Leader, LeaderOptions
from frankenpaxos_tpu.protocols.multipaxos.proxy_leader import (
    ProxyLeader,
    ProxyLeaderOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.proxy_replica import (
    ProxyReplica,
    ProxyReplicaOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.read_batcher import (
    ReadBatcher,
    ReadBatchingScheme,
)
from frankenpaxos_tpu.protocols.multipaxos.replica import (
    Replica,
    ReplicaOptions,
)

__all__ = [
    "Acceptor",
    "AcceptorOptions",
    "Batcher",
    "BatcherOptions",
    "Client",
    "ClientOptions",
    "DistributionScheme",
    "Leader",
    "LeaderOptions",
    "MultiPaxosConfig",
    "ProxyLeader",
    "ProxyLeaderOptions",
    "ProxyReplica",
    "ProxyReplicaOptions",
    "ReadBatcher",
    "ReadBatchingScheme",
    "Replica",
    "ReplicaOptions",
]
