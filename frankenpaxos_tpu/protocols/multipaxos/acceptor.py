"""MultiPaxos Acceptor.

Reference behavior: multipaxos/Acceptor.scala:59-255. Per-slot
{vote_round, vote_value} state, a single monotone ``round``, nacks for
stale rounds (Phase2a nacks go to the round's *leader*, not the proxy
leader that forwarded it), ``max_voted_slot`` serving quorum reads.
"""

from __future__ import annotations

import dataclasses
try:
    from sortedcontainers import SortedDict  # type: ignore[import-untyped]
except ImportError:  # stripped environments: pure-Python fallback
    from frankenpaxos_tpu.utils.sorted_compat import SortedDict

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    BatchMaxSlotReply,
    BatchMaxSlotRequest,
    CommandBatchOrNoop,
    MaxSlotReply,
    MaxSlotRequest,
    Nack,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2aRun,
    Phase2b,
    Phase2bRange,
    Phase2bVotes,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    decode_value,
    decode_value_array,
    encode_value,
    encode_value_array,
)
from frankenpaxos_tpu.reconfig import (
    decode_epoch_config,
    encode_epoch_config,
    EpochAck,
    EpochCommit,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.wal import (
    DurableRole,
    WalEpoch,
    WalPromise,
    WalSnapshot,
    WalVote,
    WalVoteRun,
)


@dataclasses.dataclass(frozen=True)
class AcceptorOptions:
    measure_latencies: bool = True
    # Ack contiguous same-round Phase2a runs voted within one event-loop
    # drain as ONE Phase2bRange per proxy leader (see
    # messages.Phase2bRange). Lone votes still go as plain Phase2bs, so
    # per-message delivery (the adversarial sims) is byte-identical to
    # the reference shape.
    range_phase2bs: bool = True


@dataclasses.dataclass
class _VoteState:
    vote_round: int
    vote_value: CommandBatchOrNoop


class Acceptor(Actor, DurableRole):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 options: AcceptorOptions = AcceptorOptions(),
                 collectors: Collectors | None = None, wal=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        collectors = collectors or FakeCollectors()
        self.metrics_latency = collectors.summary(
            "multipaxos_acceptor_requests_latency_seconds", labels=("type",))
        self.metrics_requests = collectors.counter(
            "multipaxos_acceptor_requests_total", labels=("type",))
        self.group_index = next(
            g for g, group in enumerate(config.acceptor_addresses)
            if address in group)
        self.index = list(
            config.acceptor_addresses[self.group_index]).index(address)
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.round = -1
        self.states: SortedDict = SortedDict()  # slot -> _VoteState
        # Committed reconfiguration epochs (reconfig/):
        # epoch id -> EpochCommit, round-monotone per id. The acceptor
        # is a MATCHMAKER for the epoch map: entries are WAL'd before
        # the EpochAck leaves (group commit), and every Phase1b reports
        # them so a new leader's read quorum always discovers activated
        # epochs (the Flexible-Paxos intersection condition).
        self._epoch_commits: dict[int, EpochCommit] = {}
        # Run-voted state (Phase2aRun): start -> (end, round, values) --
        # one O(1) record per run instead of per-slot _VoteStates. A
        # slot's authoritative vote is the HIGHEST round across both
        # stores (see _voted_info); the acceptor's monotone ``round``
        # means later votes never have a lower round, and equal-round
        # double-votes carry the same value (one proposal per
        # (slot, round)), so max-round resolution is exact.
        self._voted_runs: SortedDict = SortedDict()
        self.max_voted_slot = -1
        # Phase2b acks staged during this drain: dst -> [(slot, round)].
        self._pending_phase2bs: dict[Address, list] = {}
        # Durability (wal/): promises and votes append to the WAL as
        # they are handled, and every ack that DEPENDS on one is held
        # back until on_drain's single group-commit fsync releases it
        # (DurableRole) -- a crashed acceptor can therefore never have
        # acked state it will not recover. wal=None (the default) is
        # the reference's in-memory behavior.
        self._wal_init(wal)
        if wal is not None:
            self._recover_from_wal()

    # --- durability -------------------------------------------------------
    def _recover_from_wal(self) -> None:
        for record in self.wal.recover(self.logger):
            if isinstance(record, WalSnapshot):
                # A compaction base: everything replayed so far is
                # superseded state re-logged after this marker.
                self.round = -1
                self.states.clear()
                self._voted_runs.clear()
                self.max_voted_slot = -1
            elif isinstance(record, WalPromise):
                self.round = max(self.round, record.round)
            elif isinstance(record, WalVote):
                self.round = max(self.round, record.round)
                self.states[record.slot] = _VoteState(
                    record.round, decode_value(record.value))
                self.max_voted_slot = max(self.max_voted_slot,
                                          record.slot)
            elif isinstance(record, WalVoteRun):
                self.round = max(self.round, record.round)
                self._store_run(record.start_slot, record.round,
                                decode_value_array(record.values))
            elif isinstance(record, WalEpoch):
                epoch, start, f, rnd, members = decode_epoch_config(
                    record.payload)
                known = self._epoch_commits.get(epoch)
                if known is None or rnd > known.round:
                    self._epoch_commits[epoch] = EpochCommit(
                        epoch=epoch, start_slot=start, f=f, round=rnd,
                        members=members)
            else:
                self.logger.fatal(
                    f"unexpected acceptor WAL record {record!r}")

    def _wal_compact(self) -> None:
        """Rewrite the log as one snapshot marker + the live voted
        state (one fsync), reclaiming every older segment."""
        records = [WalPromise(round=self.round)]
        for epoch in sorted(self._epoch_commits):
            c = self._epoch_commits[epoch]
            records.append(WalEpoch(payload=encode_epoch_config(
                c.epoch, c.start_slot, c.f, c.round, c.members)))
        for start, (end, rnd, values) in self._voted_runs.items():
            records.append(WalVoteRun(
                start_slot=start, stride=1, round=rnd,
                values=encode_value_array(values)))
        for slot, vs in self.states.items():
            records.append(WalVote(
                slot=slot, round=vs.vote_round,
                value=encode_value(vs.vote_value)))
        self.wal.compact(WalSnapshot(payload=b""), records)

    def receive(self, src: Address, message) -> None:
        # timed(label) handler latency summaries (Leader.scala:281-293).
        if self.options.measure_latencies:
            with self.metrics_latency.labels(
                    type(message).__name__).time():
                self._receive_impl(src, message)
        else:
            self._receive_impl(src, message)

    def _receive_impl(self, src: Address, message) -> None:
        if isinstance(message, Phase1a):
            self.metrics_requests.labels("Phase1a").inc()
            self._handle_phase1a(src, message)
        elif isinstance(message, Phase2a):
            self.metrics_requests.labels("Phase2a").inc()
            self._handle_phase2a(src, message)
        elif isinstance(message, Phase2aRun):
            self.metrics_requests.labels("Phase2aRun").inc()
            self._handle_phase2a_run(src, message)
        elif isinstance(message, MaxSlotRequest):
            self.metrics_requests.labels("MaxSlotRequest").inc()
            self._handle_max_slot_request(src, message)
        elif isinstance(message, BatchMaxSlotRequest):
            self.metrics_requests.labels("BatchMaxSlotRequest").inc()
            self._handle_batch_max_slot_request(src, message)
        elif isinstance(message, EpochCommit):
            self.metrics_requests.labels("EpochCommit").inc()
            self._handle_epoch_commit(src, message)
        else:
            self.logger.fatal(f"unexpected acceptor message {message!r}")

    def _handle_epoch_commit(self, src: Address,
                             commit: EpochCommit) -> None:
        """Store one epoch map entry (round-monotone per epoch id),
        WAL it, and ack only after the drain's group commit -- the
        matchmaker write: f+1 of these durable acks IS the epoch's
        commit point."""
        if commit.round < self.round:
            # A stale leader defining epochs: nack so it re-runs Phase1
            # (mirroring the Phase2a round check).
            self.send(src, Nack(round=self.round))
            return
        known = self._epoch_commits.get(commit.epoch)
        if known is None or commit.round > known.round:
            self._epoch_commits[commit.epoch] = commit
            if self.wal is not None and known != commit:
                self.wal.append(WalEpoch(payload=encode_epoch_config(
                    commit.epoch, commit.start_slot, commit.f,
                    commit.round, commit.members)))
        elif known is not None and commit.round == known.round \
                and known != commit:
            self.logger.fatal(
                f"conflicting EpochCommits at one round: {known!r} "
                f"vs {commit!r}")
        # Duplicate commits re-ack (the leader's resend protocol).
        self._wal_send(src, EpochAck(epoch=commit.epoch,
                                     round=commit.round))

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round < self.round:
            self.logger.debug(
                f"acceptor got Phase1a in round {phase1a.round} but is in "
                f"round {self.round}")
            self.send(src, Nack(round=self.round))
            return
        if self.wal is not None and phase1a.round > self.round:
            self.wal.append(WalPromise(round=phase1a.round))
        self.round = phase1a.round
        # The promise must be durable before the leader may trust it
        # (a crashed acceptor re-promising a lower round would let two
        # leaders both believe they own a round): held for group
        # commit.
        self._wal_send(src, Phase1b(
            group_index=self.group_index, acceptor_index=self.index,
            round=self.round,
            info=self._voted_info(phase1a.chosen_watermark),
            epochs=tuple(self._epoch_commits[e]
                         for e in sorted(self._epoch_commits))))

    def _voted_info(self, minimum: int) -> tuple:
        """Every voted slot >= ``minimum`` with its HIGHEST-round vote,
        merging the per-slot store and the run store (a failover that
        ignored run votes would recover Noop over accepted values --
        data loss). Recovery-only cold path, so runs expand per slot
        here and nowhere else."""
        best: dict[int, tuple] = {
            slot: (self.states[slot].vote_round,
                   self.states[slot].vote_value)
            for slot in self.states.irange(minimum=minimum)}
        for start, (end, rnd, values) in self._voted_runs.items():
            if end <= minimum:
                continue
            for slot in range(max(start, minimum), end):
                cur = best.get(slot)
                if cur is None or rnd > cur[0]:
                    best[slot] = (rnd, values[slot - start])
        return tuple(
            Phase1bSlotInfo(slot=slot, vote_round=rnd, vote_value=value)
            for slot, (rnd, value) in sorted(best.items()))

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            self.logger.debug(
                f"acceptor got Phase2a in round {phase2a.round} but is in "
                f"round {self.round}")
            # Nack the round's leader, not the forwarding proxy leader
            # (Acceptor.scala:184-200).
            leader = self.config.leader_addresses[
                self.round_system.leader(phase2a.round)]
            self.send(leader, Nack(round=self.round))
            return
        self.round = phase2a.round
        self.states[phase2a.slot] = _VoteState(vote_round=self.round,
                                               vote_value=phase2a.value)
        self.max_voted_slot = max(self.max_voted_slot, phase2a.slot)
        if self.wal is not None:
            self.wal.append(WalVote(
                slot=phase2a.slot, round=self.round,
                value=encode_value(phase2a.value)))
        if self.options.range_phase2bs:
            # Stage the ack; on_drain coalesces contiguous runs per
            # destination into Phase2bRanges (and, durable, releases
            # them only after the drain's group commit).
            self._pending_phase2bs.setdefault(src, []).append(
                (phase2a.slot, self.round))
        else:
            self._wal_send(src, Phase2b(group_index=self.group_index,
                                        acceptor_index=self.index,
                                        slot=phase2a.slot,
                                        round=self.round))

    def _handle_phase2a_run(self, src: Address, run: Phase2aRun) -> None:
        """A whole contiguous proposal run in one O(1) update: one round
        check, one run record, one ranged ack -- the per-drain shape of
        Acceptor.scala:184-220's per-slot handlePhase2a."""
        if run.round < self.round:
            leader = self.config.leader_addresses[
                self.round_system.leader(run.round)]
            self.send(leader, Nack(round=self.round))
            return
        self.round = run.round
        end = self._store_run(run.start_slot, run.round, run.values)
        if self.wal is not None:
            # Logging the run re-encodes its value array -- a RAW COPY
            # of the inbound lazy segment, never a re-materialization.
            self.wal.append(WalVoteRun(
                start_slot=run.start_slot, stride=1, round=run.round,
                values=encode_value_array(run.values)))
        # Ack immediately as one range: the run is already a contiguous
        # same-round block, so drain-end staging (whose merge loop is
        # per-slot) would cost Python without saving messages. Durable
        # mode holds it for the drain's group commit instead.
        self._wal_send(src, Phase2bRange(group_index=self.group_index,
                                         acceptor_index=self.index,
                                         slot_start_inclusive=run.start_slot,
                                         slot_end_exclusive=end,
                                         round=run.round))

    def _store_run(self, start_slot: int, round: int, values) -> int:
        """Merge one contiguous voted run into the run store; returns
        the run's exclusive end. Shared by the live Phase2aRun handler
        and WAL replay so truncation-tail semantics cannot drift."""
        end = start_slot + len(values)
        old = self._voted_runs.get(start_slot)
        self._voted_runs[start_slot] = (end, round, values)
        if old is not None and old[0] > end:
            # A shorter same-start run replaces a longer record (a
            # re-proposed prefix after leader change): the non-overlapped
            # voted tail [end, old_end) must survive as its own record,
            # or Phase1 recovery would lose those votes (choosing Noop
            # over accepted values). ``end`` cannot equal an existing
            # start (same-start keys collide only at run.start_slot), so
            # this insert never clobbers a longer record.
            old_end, old_round, old_values = old
            tail = old_values[end - start_slot:]
            if self._voted_runs.get(end) is None:
                self._voted_runs[end] = (old_end, old_round, tail)
            else:
                # A record already starts at ``end``: spill the tail
                # into the per-slot store instead of clobbering it
                # (_voted_info max-round-merges both stores).
                for off, slot in enumerate(range(end, old_end)):
                    cur = self.states.get(slot)
                    if cur is None or cur.vote_round < old_round:
                        self.states[slot] = _VoteState(old_round,
                                                       tail[off])
        self.max_voted_slot = max(self.max_voted_slot, end - 1)
        return end

    def on_drain(self) -> None:
        pending, self._pending_phase2bs = self._pending_phase2bs, {}
        for dst, acks in pending.items():
            acks.sort()
            runs = self._runs_of(acks)
            # A heavily FRAGMENTED drain (thrifty sampling shreds the
            # proxy's contiguous Phase2a run into short per-acceptor
            # pieces) ships as ONE packed-array message instead of one
            # message per run: the native vote codec packs here and the
            # ProxyLeader unpacks straight into its tracker's arrays --
            # per-vote Python disappears from both sides.
            if len(runs) > 4 and len(acks) >= 16:
                import numpy as np

                from frankenpaxos_tpu import native

                slots = np.fromiter((s for s, _ in acks), dtype=np.int64,
                                    count=len(acks))
                rounds = np.fromiter((r for _, r in acks), dtype=np.int32,
                                     count=len(acks))
                self._wal_send(dst, Phase2bVotes(
                    group_index=self.group_index,
                    acceptor_index=self.index,
                    packed=native.pack_votes2(slots, rounds)))
                continue
            for run in runs:
                if len(run) == 1:
                    self._wal_send(dst, Phase2b(
                        group_index=self.group_index,
                        acceptor_index=self.index,
                        slot=run[0][0], round=run[0][1]))
                else:
                    self._wal_send(dst, Phase2bRange(
                        group_index=self.group_index,
                        acceptor_index=self.index,
                        slot_start_inclusive=run[0][0],
                        slot_end_exclusive=run[-1][0] + 1,
                        round=run[0][1]))
        # GROUP COMMIT (DurableRole): one fsync covers every record
        # this drain appended, then -- and only then -- the acks it
        # produced go out.
        self._wal_drain()

    @staticmethod
    def _runs_of(acks: list) -> list:
        """Split sorted (slot, round) acks into contiguous same-round
        runs."""
        runs = []
        start = 0
        for i in range(1, len(acks) + 1):
            if (i < len(acks)
                    and acks[i][0] == acks[i - 1][0] + 1
                    and acks[i][1] == acks[i - 1][1]):
                continue
            runs.append(acks[start:i])
            start = i
        return runs

    def _handle_max_slot_request(self, src: Address,
                                 request: MaxSlotRequest) -> None:
        self.send(src, MaxSlotReply(command_id=request.command_id,
                                    group_index=self.group_index,
                                    acceptor_index=self.index,
                                    slot=self.max_voted_slot))

    def _handle_batch_max_slot_request(self, src: Address,
                                       request: BatchMaxSlotRequest) -> None:
        self.send(src, BatchMaxSlotReply(
            read_batcher_index=request.read_batcher_index,
            read_batcher_id=request.read_batcher_id,
            group_index=self.group_index,
            acceptor_index=self.index,
            slot=self.max_voted_slot))
