"""MultiPaxos cluster configuration.

Reference behavior: multipaxos/Config.scala:16-147 (role address lists,
``f``, ``flexible`` grid mode, distribution scheme, and the validation
rules) and multipaxos/DistributionScheme.scala:151-162 (Hash: roles
spread over machines and picked round-robin/randomly; Colocated: proxy
roles live with their parent role, simulating coupled MultiPaxos).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence

from frankenpaxos_tpu.quorums import Grid
from frankenpaxos_tpu.runtime.transport import Address


class DistributionScheme(enum.Enum):
    HASH = "hash"
    COLOCATED = "colocated"


@dataclasses.dataclass(frozen=True)
class MultiPaxosConfig:
    f: int
    batcher_addresses: Sequence[Address]
    read_batcher_addresses: Sequence[Address]
    leader_addresses: Sequence[Address]
    leader_election_addresses: Sequence[Address]
    proxy_leader_addresses: Sequence[Address]
    # Non-flexible: acceptor groups of 2f+1 each; slots round-robin over
    # groups. Flexible: a grid -- rows are read quorums, one-per-row sets
    # are write quorums; the log is not partitioned.
    acceptor_addresses: Sequence[Sequence[Address]]
    replica_addresses: Sequence[Address]
    proxy_replica_addresses: Sequence[Address]
    flexible: bool = False
    distribution_scheme: DistributionScheme = DistributionScheme.HASH
    # paxingest (ingest/, docs/TRANSPORT.md): disseminator roles that
    # absorb client fan-in and hand leaders pre-batched IngestRun
    # descriptors. When non-empty, clients route writes here instead of
    # to batchers/leaders. WAL-free by design -- a dead batcher costs
    # client retries (covered by retry budgets + the replica client
    # table's exactly-once), never acked-write loss, so ANY count >= 1
    # is valid (failover is the client's resend to another batcher).
    ingest_batcher_addresses: Sequence[Address] = ()

    @property
    def num_ingest_batchers(self) -> int:
        return len(self.ingest_batcher_addresses)

    @property
    def num_batchers(self) -> int:
        return len(self.batcher_addresses)

    @property
    def num_read_batchers(self) -> int:
        return len(self.read_batcher_addresses)

    @property
    def num_leaders(self) -> int:
        return len(self.leader_addresses)

    @property
    def num_proxy_leaders(self) -> int:
        return len(self.proxy_leader_addresses)

    @property
    def num_acceptor_groups(self) -> int:
        return len(self.acceptor_addresses)

    @property
    def num_replicas(self) -> int:
        return len(self.replica_addresses)

    @property
    def num_proxy_replicas(self) -> int:
        return len(self.proxy_replica_addresses)

    def quorum_grid(self) -> Grid:
        """The (group, index) grid over acceptor coordinates, flattened to
        ints ``group * row_size + index`` (flexible mode)."""
        m = len(self.acceptor_addresses[0])
        return Grid([[g * m + i for i in range(m)]
                     for g in range(self.num_acceptor_groups)])

    def check_valid(self) -> None:
        def require(cond: bool, msg: str) -> None:
            if not cond:
                raise ValueError(msg)

        f = self.f
        require(f >= 1, f"f must be >= 1. It's {f}.")
        if self.distribution_scheme == DistributionScheme.HASH:
            require(self.num_batchers == 0 or self.num_batchers >= f + 1,
                    f"num_batchers must be 0 or >= f+1. It's "
                    f"{self.num_batchers}.")
        else:
            require(self.num_batchers in (0, self.num_leaders),
                    "num_batchers must be 0 or equal num_leaders for "
                    "Colocated.")
        require(self.num_read_batchers == 0
                or self.num_read_batchers >= f + 1,
                "num_read_batchers must be 0 or >= f+1.")
        require(self.num_leaders >= f + 1, "num_leaders must be >= f+1.")
        require(len(self.leader_election_addresses) == self.num_leaders,
                "leader_election_addresses must match leader_addresses.")
        require(self.num_proxy_leaders >= f + 1,
                "num_proxy_leaders must be >= f+1.")
        if self.distribution_scheme == DistributionScheme.COLOCATED:
            require(self.num_proxy_leaders == self.num_leaders,
                    "num_proxy_leaders must equal num_leaders for Colocated.")
        require(self.num_acceptor_groups >= 1,
                "need at least one acceptor group.")
        if not self.flexible:
            for group in self.acceptor_addresses:
                require(len(group) == 2 * f + 1,
                        f"acceptor groups must have 2f+1 = {2*f+1} members; "
                        f"one has {len(group)}.")
        else:
            m = len(self.acceptor_addresses[0])
            for row in self.acceptor_addresses:
                require(len(row) == m, "grid rows must be equal-sized.")
            n = self.num_acceptor_groups
            require(min(n, m) - 1 >= f,
                    f"an {n}x{m} grid tolerates min(n,m)-1 = {min(n,m)-1} "
                    f"failures < f = {f}.")
        require(self.num_replicas >= f + 1, "num_replicas must be >= f+1.")
        require(self.num_proxy_replicas == 0
                or self.num_proxy_replicas >= f + 1,
                "num_proxy_replicas must be 0 or >= f+1.")
        if self.distribution_scheme == DistributionScheme.COLOCATED:
            require(self.num_proxy_replicas in (0, self.num_replicas),
                    "num_proxy_replicas must equal num_replicas for "
                    "Colocated.")
