"""MultiPaxos ProxyReplica: fans client replies out, off the replica's
critical path.

Reference behavior: multipaxos/ProxyReplica.scala:69-218 -- unbatch
ClientReplyBatch / ReadReplyBatch to clients (with flush-every-N
coalescing) and forward ChosenWatermark / Recover on to all leaders.
"""

from __future__ import annotations

import dataclasses

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ChosenWatermark,
    ClientReplyBatch,
    ReadReplyBatch,
    Recover,
)
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class ProxyReplicaOptions:
    flush_every_n: int = 1
    measure_latencies: bool = True


class ProxyReplica(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 options: ProxyReplicaOptions = ProxyReplicaOptions(),
                 collectors: Collectors | None = None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        collectors = collectors or FakeCollectors()
        self.metrics_latency = collectors.summary(
            "multipaxos_proxy_replica_requests_latency_seconds", labels=("type",))
        self.metrics_requests = collectors.counter(
            "multipaxos_proxy_replica_requests_total", labels=("type",))
        self._unflushed = 0
        self._unflushed_clients: set[Address] = set()

    def _send_coalesced(self, dst: Address, message) -> None:
        if self.options.flush_every_n <= 1:
            self.send(dst, message)
            return
        self.send_no_flush(dst, message)
        self._unflushed_clients.add(dst)
        self._unflushed += 1
        if self._unflushed >= self.options.flush_every_n:
            for client in self._unflushed_clients:
                self.flush(client)
            self._unflushed_clients.clear()
            self._unflushed = 0

    def receive(self, src: Address, message) -> None:
        # timed(label) handler latency summaries (Leader.scala:281-293).
        if self.options.measure_latencies:
            with self.metrics_latency.labels(
                    type(message).__name__).time():
                self._receive_impl(src, message)
        else:
            self._receive_impl(src, message)

    def _receive_impl(self, src: Address, message) -> None:
        if isinstance(message, ClientReplyBatch):
            self.metrics_requests.labels("ClientReplyBatch").inc()
            for reply in message.batch:
                self._send_coalesced(reply.command_id.client_address, reply)
        elif isinstance(message, ReadReplyBatch):
            self.metrics_requests.labels("ReadReplyBatch").inc()
            for reply in message.batch:
                self._send_coalesced(reply.command_id.client_address, reply)
        elif isinstance(message, (ChosenWatermark, Recover)):
            label = type(message).__name__
            self.metrics_requests.labels(label).inc()
            for leader in self.config.leader_addresses:
                self.send(leader, message)
        else:
            self.logger.fatal(f"unexpected proxy replica message {message!r}")
