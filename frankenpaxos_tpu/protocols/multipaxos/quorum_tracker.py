"""Pluggable Phase2b write-quorum tracking: host dict or TPU vote board.

The ProxyLeader's vote-collection loop (ProxyLeader.scala:217-258) is the
hottest code in the reference. Here it is a strategy interface with two
implementations:

  * ``DictQuorumTracker`` -- the reference's semantics verbatim: a dict
    keyed (slot, round) accumulating (group, acceptor) votes. The oracle.
  * ``TpuQuorumTracker`` -- votes buffered per event-loop drain, then one
    ``TpuQuorumChecker.record_and_check`` scatter + matmul per drain.
    Acceptor coordinates flatten to columns ``group * group_size + index``.
    In non-flexible mode only a slot's own group is ever messaged, so a
    universe-wide count >= f+1 threshold is exactly the per-group f+1
    quorum; in flexible mode the grid write-spec applies.

Both report each (slot, round)'s quorum exactly once.
"""

from __future__ import annotations

import abc

import numpy as np

from frankenpaxos_tpu.quorums import QuorumSpec
from frankenpaxos_tpu.quorums.spec import ANY
from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig


class QuorumTracker(abc.ABC):
    """Tracks Phase2b votes; reports slots whose quorum completes."""

    @abc.abstractmethod
    def record(self, slot: int, round: int, group_index: int,
               acceptor_index: int) -> None:
        ...

    @abc.abstractmethod
    def drain(self) -> list[tuple[int, int]]:
        """Flush buffered votes; return [(slot, round)] newly at quorum."""


class DictQuorumTracker(QuorumTracker):
    def __init__(self, config: MultiPaxosConfig):
        self.config = config
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        # (slot, round) -> set of (group, index); None once chosen.
        self.states: dict[tuple[int, int], set | None] = {}
        self._newly: list[tuple[int, int]] = []

    def record(self, slot, round, group_index, acceptor_index) -> None:
        key = (slot, round)
        votes = self.states.get(key)
        if votes is None and key in self.states:
            return  # already chosen (Done)
        if votes is None:
            votes = set()
            self.states[key] = votes
        votes.add((group_index, acceptor_index))
        if self.config.flexible:
            flat = {g * self._row_size + i for g, i in votes}
            if not self.grid.is_superset_of_write_quorum(flat):
                return
        else:
            if len(votes) < self.config.f + 1:
                return
        self.states[key] = None  # Done
        self._newly.append(key)

    def drain(self) -> list[tuple[int, int]]:
        newly, self._newly = self._newly, []
        return newly


class TpuQuorumTracker(QuorumTracker):
    """``pipelined=True`` decouples device round-trips from the event
    loop: each drain DISPATCHES its votes asynchronously (returning [])
    and enqueues an in-flight record; the caller collects completed
    dispatches via :meth:`take_dispatch` + :meth:`collect` -- from a
    worker thread (ProxyLeader posts results back onto the event loop)
    or a flush timer. This hides the device-link latency behind the
    event loop -- essential when the accelerator sits across a high-RTT
    link -- at the cost of one dispatch of added choose latency."""

    def __init__(self, config: MultiPaxosConfig, window: int = 1 << 20,
                 pipelined: bool = False):
        import collections

        self.config = config
        self.pipelined = pipelined
        # In-flight dispatches: (slots, rounds, device per-vote masks).
        # append/popleft are GIL-atomic, so a collector thread may pop
        # while the event loop appends.
        self._inflight = collections.deque()
        self._row_size = len(config.acceptor_addresses[0])
        num_cols = config.num_acceptor_groups * self._row_size
        universe = tuple(range(num_cols))
        if config.flexible:
            spec = config.quorum_grid().write_spec().reindexed(universe)
        else:
            spec = QuorumSpec(
                masks=np.ones((1, num_cols), dtype=np.uint8),
                thresholds=np.array([config.f + 1], dtype=np.int32),
                combine=ANY,
                universe=universe,
            )
        # Lazy: keeps jax out of dict-backend role processes entirely
        # (it costs seconds of startup per process).
        from frankenpaxos_tpu.ops.quorum import TpuQuorumChecker

        self.checker = TpuQuorumChecker(spec, window=window)
        self._slots: list[int] = []
        self._cols: list[int] = []
        self._rounds: list[int] = []
        # Pre-compile the smallest (64-wide) dense and sparse kernels at
        # construction -- before client traffic -- so the first real
        # drains don't stall several seconds on XLA compiles. Votes land
        # at round -1 (below any real round), and release() clears the
        # touched columns.
        # Max columns per device call: oversized drains are chunked to
        # this, so ONLY the prewarmed kernel buckets (64, max_chunk)
        # ever compile -- an unexpected width compiling mid-run stalls
        # the event loop for seconds over a remote device link.
        self.max_chunk = 256
        for width in (1, self.max_chunk):
            warm = np.zeros((self.checker.num_nodes, width),
                            dtype=np.uint8)
            warm[0, 0] = 1
            self.checker.record_block(0, warm, vote_round=-1)
            self.checker.record_and_check([0] * width, [0] * width,
                                          [-1] * width)
        self.checker.release(np.arange(self.max_chunk))

    def record(self, slot, round, group_index, acceptor_index) -> None:
        self._slots.append(slot)
        self._cols.append(group_index * self._row_size + acceptor_index)
        self._rounds.append(round)

    def drain(self) -> list[tuple[int, int]]:
        """One device call (ideally) per event-loop drain.

        Steady-state Phase2b streams cover a contiguous slot run in one
        round (Leader.scala:331-408 allocates slots contiguously), which
        maps onto the dense ``record_block`` path -- a slice update plus
        one matmul, no scatter. Votes outside the dominant round or a
        sufficiently dense run fall back to the sparse scatter path.
        """
        if not self._slots:
            return []
        slots = np.asarray(self._slots, dtype=np.int64)
        cols = np.asarray(self._cols, dtype=np.int32)
        rounds = np.asarray(self._rounds, dtype=np.int32)
        device_parts = []  # (index array into this drain, device mask)

        # Dense candidate: the drain's dominant round.
        round_values, round_counts = np.unique(rounds, return_counts=True)
        dom = int(round_values[np.argmax(round_counts)])
        dense = rounds == dom
        lo = int(slots[dense].min())
        hi = int(slots[dense].max())
        width = hi - lo + 1
        window = self.checker.window
        # Worth the dense path when the run is reasonably filled, fits a
        # prewarmed kernel bucket, and doesn't straddle the ring end
        # (record_block's contract).
        bucket = 64 if width <= 64 else self.max_chunk
        if (width <= min(self.max_chunk, max(64, 4 * int(dense.sum())))
                and lo % window + bucket <= window):
            # Build the block at the prewarmed bucket width directly
            # (all-zero padding columns are untouched by the kernel).
            block = np.zeros((self.checker.num_nodes, bucket),
                             dtype=np.uint8)
            block[cols[dense], slots[dense] - lo] = 1
            newly = self.checker.record_block_async(lo, block,
                                                    vote_round=dom)
            # Device results stay at the padded bucket shape; the
            # per-vote positions are applied host-side in collect() (a
            # device gather here would compile per distinct length).
            device_parts.append((np.flatnonzero(dense), newly,
                                 slots[dense] - lo))
            rest = ~dense
        else:
            rest = np.ones(slots.shape[0], dtype=bool)
        rest_index = np.flatnonzero(rest)
        # Chunk the sparse tail so only prewarmed buckets ever run.
        for at in range(0, rest_index.size, self.max_chunk):
            chunk = rest_index[at:at + self.max_chunk]
            device_parts.append((chunk,
                                 self.checker.record_and_check_async(
                                     slots[chunk], cols[chunk],
                                     rounds[chunk],
                                     pad_to=(64 if chunk.size <= 64
                                             else self.max_chunk)),
                                 np.arange(chunk.size)))

        dispatch = (self._slots, self._rounds, device_parts)
        self._slots, self._cols, self._rounds = [], [], []
        if self.pipelined:
            self._inflight.append(dispatch)
            return []
        return self.collect(dispatch)

    def has_pending(self) -> bool:
        return bool(self._inflight)

    def take_dispatch(self):
        """Pop the oldest in-flight dispatch (None if empty); pass it to
        :meth:`collect`. Safe to call from a collector thread."""
        try:
            return self._inflight.popleft()
        except IndexError:
            return None

    def collect(self, dispatch) -> list[tuple[int, int]]:
        """Fetch a dispatch's results (blocking on the device if they
        are not done yet) and dedup per slot."""
        drain_slots, drain_rounds, device_parts = dispatch
        hits = np.zeros(len(drain_slots), dtype=bool)
        for index, mask, positions in device_parts:
            hits[index] = np.asarray(mask)[positions]
        out: list[tuple[int, int]] = []
        seen: set[int] = set()
        for slot, round, hit in zip(drain_slots, drain_rounds, hits):
            if hit and slot not in seen:
                seen.add(slot)
                out.append((slot, round))
        return out
