"""Pluggable Phase2b write-quorum tracking: host dict or TPU vote board.

The ProxyLeader's vote-collection loop (ProxyLeader.scala:217-258) is the
hottest code in the reference. Here it is a strategy interface with two
implementations:

  * ``DictQuorumTracker`` -- the reference's semantics verbatim: a dict
    keyed (slot, round) accumulating (group, acceptor) votes. The oracle.
  * ``TpuQuorumTracker`` -- votes buffered per event-loop drain, then one
    ``TpuQuorumChecker.record_and_check`` scatter + matmul per drain.
    Acceptor coordinates flatten to columns ``group * group_size + index``.
    In non-flexible mode only a slot's own group is ever messaged, so a
    universe-wide count >= f+1 threshold is exactly the per-group f+1
    quorum; in flexible mode the grid write-spec applies.

Both report each (slot, round)'s quorum exactly once.
"""

from __future__ import annotations

import abc

import numpy as np

from frankenpaxos_tpu.quorums import QuorumSpec
from frankenpaxos_tpu.quorums.spec import ANY
from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig


class QuorumTracker(abc.ABC):
    """Tracks Phase2b votes; reports slots whose quorum completes."""

    @abc.abstractmethod
    def record(self, slot: int, round: int, group_index: int,
               acceptor_index: int) -> None:
        ...

    @abc.abstractmethod
    def drain(self) -> list[tuple[int, int]]:
        """Flush buffered votes; return [(slot, round)] newly at quorum."""


class DictQuorumTracker(QuorumTracker):
    def __init__(self, config: MultiPaxosConfig):
        self.config = config
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        # (slot, round) -> set of (group, index); None once chosen.
        self.states: dict[tuple[int, int], set | None] = {}
        self._newly: list[tuple[int, int]] = []

    def record(self, slot, round, group_index, acceptor_index) -> None:
        key = (slot, round)
        votes = self.states.get(key)
        if votes is None and key in self.states:
            return  # already chosen (Done)
        if votes is None:
            votes = set()
            self.states[key] = votes
        votes.add((group_index, acceptor_index))
        if self.config.flexible:
            flat = {g * self._row_size + i for g, i in votes}
            if not self.grid.is_superset_of_write_quorum(flat):
                return
        else:
            if len(votes) < self.config.f + 1:
                return
        self.states[key] = None  # Done
        self._newly.append(key)

    def drain(self) -> list[tuple[int, int]]:
        newly, self._newly = self._newly, []
        return newly


class TpuQuorumTracker(QuorumTracker):
    def __init__(self, config: MultiPaxosConfig, window: int = 1 << 20):
        self.config = config
        self._row_size = len(config.acceptor_addresses[0])
        num_cols = config.num_acceptor_groups * self._row_size
        universe = tuple(range(num_cols))
        if config.flexible:
            spec = config.quorum_grid().write_spec().reindexed(universe)
        else:
            spec = QuorumSpec(
                masks=np.ones((1, num_cols), dtype=np.uint8),
                thresholds=np.array([config.f + 1], dtype=np.int32),
                combine=ANY,
                universe=universe,
            )
        # Lazy: keeps jax out of dict-backend role processes entirely
        # (it costs seconds of startup per process).
        from frankenpaxos_tpu.ops.quorum import TpuQuorumChecker

        self.checker = TpuQuorumChecker(spec, window=window)
        self._slots: list[int] = []
        self._cols: list[int] = []
        self._rounds: list[int] = []

    def record(self, slot, round, group_index, acceptor_index) -> None:
        self._slots.append(slot)
        self._cols.append(group_index * self._row_size + acceptor_index)
        self._rounds.append(round)

    def drain(self) -> list[tuple[int, int]]:
        """One device call (ideally) per event-loop drain.

        Steady-state Phase2b streams cover a contiguous slot run in one
        round (Leader.scala:331-408 allocates slots contiguously), which
        maps onto the dense ``record_block`` path -- a slice update plus
        one matmul, no scatter. Votes outside the dominant round or a
        sufficiently dense run fall back to the sparse scatter path.
        """
        if not self._slots:
            return []
        slots = np.asarray(self._slots, dtype=np.int64)
        cols = np.asarray(self._cols, dtype=np.int32)
        rounds = np.asarray(self._rounds, dtype=np.int32)
        hits = np.zeros(slots.shape[0], dtype=bool)

        # Dense candidate: the drain's dominant round.
        round_values, round_counts = np.unique(rounds, return_counts=True)
        dom = int(round_values[np.argmax(round_counts)])
        dense = rounds == dom
        lo = int(slots[dense].min())
        hi = int(slots[dense].max())
        width = hi - lo + 1
        window = self.checker.window
        # Worth the dense path when the run is reasonably filled and
        # doesn't straddle the ring end (record_block's contract).
        if (width <= max(64, 4 * int(dense.sum()))
                and lo % window + width <= window):
            block = np.zeros((self.checker.num_nodes, width),
                             dtype=np.uint8)
            block[cols[dense], slots[dense] - lo] = 1
            newly = self.checker.record_block(lo, block, vote_round=dom)
            hits[dense] = newly[slots[dense] - lo]
            rest = ~dense
        else:
            rest = np.ones(slots.shape[0], dtype=bool)
        if rest.any():
            hits[rest] = self.checker.record_and_check(
                slots[rest], cols[rest], rounds[rest])

        out: list[tuple[int, int]] = []
        seen: set[int] = set()
        for slot, round, hit in zip(self._slots, self._rounds, hits):
            if hit and slot not in seen:
                seen.add(slot)
                out.append((slot, round))
        self._slots, self._cols, self._rounds = [], [], []
        return out
