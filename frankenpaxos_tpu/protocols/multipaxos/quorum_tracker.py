"""Pluggable Phase2b write-quorum tracking: host dict or TPU vote board.

The ProxyLeader's vote-collection loop (ProxyLeader.scala:217-258) is the
hottest code in the reference. Here it is a strategy interface with two
implementations:

  * ``DictQuorumTracker`` -- the reference's semantics verbatim: a dict
    keyed (slot, round) accumulating (group, acceptor) votes. The oracle.
  * ``TpuQuorumTracker`` -- votes buffered per event-loop drain, then one
    ``TpuQuorumChecker.record_and_check`` scatter + matmul per drain.
    Acceptor coordinates flatten to columns ``group * group_size + index``.
    In non-flexible mode only a slot's own group is ever messaged, so a
    universe-wide count >= f+1 threshold is exactly the per-group f+1
    quorum; in flexible mode the grid write-spec applies.

Both report each (slot, round)'s quorum exactly once.
"""

from __future__ import annotations

import abc

import numpy as np

from frankenpaxos_tpu.quorums import QuorumSpec
from frankenpaxos_tpu.quorums.spec import ANY
from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig


class QuorumTracker(abc.ABC):
    """Tracks Phase2b votes; reports slots whose quorum completes."""

    @abc.abstractmethod
    def record(self, slot: int, round: int, group_index: int,
               acceptor_index: int) -> None:
        ...

    def record_range(self, slot_start: int, slot_end: int, round: int,
                     group_index: int, acceptor_index: int) -> None:
        """One acceptor's votes for slots [slot_start, slot_end) in one
        round (a Phase2bRange). Default: per-slot expansion."""
        for slot in range(slot_start, slot_end):
            self.record(slot, round, group_index, acceptor_index)

    @abc.abstractmethod
    def drain(self) -> list[tuple[int, int]]:
        """Flush buffered votes; return [(slot, round)] newly at quorum."""


class DictQuorumTracker(QuorumTracker):
    def __init__(self, config: MultiPaxosConfig):
        self.config = config
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        # (slot, round) -> set of (group, index); None once chosen.
        self.states: dict[tuple[int, int], set | None] = {}
        self._newly: list[tuple[int, int]] = []

    def record(self, slot, round, group_index, acceptor_index) -> None:
        key = (slot, round)
        votes = self.states.get(key)
        if votes is None and key in self.states:
            return  # already chosen (Done)
        if votes is None:
            votes = set()
            self.states[key] = votes
        votes.add((group_index, acceptor_index))
        if self.config.flexible:
            flat = {g * self._row_size + i for g, i in votes}
            if not self.grid.is_superset_of_write_quorum(flat):
                return
        else:
            if len(votes) < self.config.f + 1:
                return
        self.states[key] = None  # Done
        self._newly.append(key)

    def drain(self) -> list[tuple[int, int]]:
        newly, self._newly = self._newly, []
        return newly


class TpuQuorumTracker(QuorumTracker):
    """``pipelined=True`` decouples device round-trips from the event
    loop: each drain DISPATCHES its votes asynchronously (returning [])
    and enqueues an in-flight record; the caller collects completed
    dispatches via :meth:`take_dispatch` + :meth:`collect` -- from a
    worker thread (ProxyLeader posts results back onto the event loop)
    or a flush timer. This hides the device-link latency behind the
    event loop -- essential when the accelerator sits across a high-RTT
    link -- at the cost of one dispatch of added choose latency."""

    def __init__(self, config: MultiPaxosConfig, window: int = 1 << 20,
                 pipelined: bool = False, mesh=None):
        import collections

        self.config = config
        self.pipelined = pipelined
        # In-flight dispatches: (slots, rounds, device per-vote masks).
        # append/popleft are GIL-atomic, so a collector thread may pop
        # while the event loop appends.
        self._inflight = collections.deque()
        self._row_size = len(config.acceptor_addresses[0])
        num_cols = config.num_acceptor_groups * self._row_size
        universe = tuple(range(num_cols))
        if config.flexible:
            spec = config.quorum_grid().write_spec().reindexed(universe)
        else:
            spec = QuorumSpec(
                masks=np.ones((1, num_cols), dtype=np.uint8),
                thresholds=np.array([config.f + 1], dtype=np.int32),
                combine=ANY,
                universe=universe,
            )
        # Lazy: keeps jax out of dict-backend role processes entirely
        # (it costs seconds of startup per process).
        from frankenpaxos_tpu.ops.quorum import TpuQuorumChecker

        self.checker = TpuQuorumChecker(spec, window=window, mesh=mesh)
        self._slots: list[int] = []
        self._cols: list[int] = []
        self._rounds: list[int] = []
        # Ranged votes (Phase2bRange): [(start, end, col, round)] --
        # O(1) Python per message, expanded vectorized at drain time.
        self._ranges: list[tuple[int, int, int, int]] = []
        # Kernel width buckets. Drains are chunked to these so ONLY the
        # prewarmed widths ever compile -- an unexpected width compiling
        # mid-run stalls the event loop for seconds over a remote device
        # link. Dense buckets go wide (a contiguous 4k-slot run is one
        # slice+matmul call); the sparse scatter tail stays narrow.
        self.max_chunk = 256
        self.dense_buckets = tuple(
            b for b in (64, 256, 1024, 4096) if b <= window)
        if not self.dense_buckets:
            raise ValueError(f"window must be >= 64 (got {window}): the "
                             f"smallest prewarmed dense kernel bucket is "
                             f"64 columns")
        self.max_dense = self.dense_buckets[-1]
        # A dominant-round cluster goes dense when it's at least this
        # filled; emptier clusters cost fewer device calls via scatter.
        self.min_fill = 0.25
        # Pre-compile every bucket at construction -- before client
        # traffic -- so the first real drains don't stall on XLA
        # compiles. Votes land at round -1 (below any real round), and
        # release() clears the touched columns (including the ring
        # owners the prewarm claimed).
        for width in self.dense_buckets:
            warm = np.zeros((self.checker.num_nodes, width),
                            dtype=np.uint8)
            warm[0, 0] = 1
            self.checker.record_block(0, warm, vote_round=-1)
        for width in (1, self.max_chunk):
            self.checker.record_and_check([0] * width, [0] * width,
                                          [-1] * width)
        self.checker.release(np.arange(self.max_dense))

    def record(self, slot, round, group_index, acceptor_index) -> None:
        self._slots.append(slot)
        self._cols.append(group_index * self._row_size + acceptor_index)
        self._rounds.append(round)

    def record_range(self, slot_start, slot_end, round, group_index,
                     acceptor_index) -> None:
        self._ranges.append((slot_start, slot_end,
                             group_index * self._row_size
                             + acceptor_index, round))

    def drain(self) -> list[tuple[int, int]]:
        """A handful of device calls (ideally one) per event-loop drain.

        Steady-state Phase2b streams cover contiguous slot runs in one
        round (Leader.scala:331-408 allocates slots contiguously), which
        map onto the dense ``record_block`` path -- a slice update plus
        one matmul, no scatter. The drain's dominant round is sorted and
        clustered into dense runs chunked at prewarmed bucket widths (up
        to ``max_dense`` slots per call); sparse stragglers and
        off-round votes go through the scatter path. Sparse votes in
        rounds OLDER than the dominant round dispatch BEFORE the dense
        block so an old-round quorum completing in this drain is
        reported before the newer round's preemption clears it
        (matching DictQuorumTracker's arrival-order liveness).
        """
        if not self._slots and not self._ranges:
            return []
        slots = np.asarray(self._slots, dtype=np.int64)
        cols = np.asarray(self._cols, dtype=np.int32)
        rounds = np.asarray(self._rounds, dtype=np.int32)
        if self._ranges:
            # Expand ranged votes vectorized (the whole point of
            # Phase2bRange: no per-slot Python before this point).
            parts_s = [slots] if slots.size else []
            parts_c = [cols] if slots.size else []
            parts_r = [rounds] if slots.size else []
            for start, end, col, rnd in self._ranges:
                width = end - start
                parts_s.append(np.arange(start, end, dtype=np.int64))
                parts_c.append(np.full(width, col, dtype=np.int32))
                parts_r.append(np.full(width, rnd, dtype=np.int32))
            slots = np.concatenate(parts_s)
            cols = np.concatenate(parts_c)
            rounds = np.concatenate(parts_r)
        device_parts = []  # (index array into this drain, device mask,
        #                     positions into the mask)

        # The drain's dominant round (fast path: single-round drain).
        if rounds[0] == rounds[-1] and (rounds == rounds[0]).all():
            dom = int(rounds[0])
            # Steady-state fast path: one round, one reasonably filled
            # contiguous run fitting one dense bucket -- skip the sort
            # and cluster walk entirely (the common shape: a wave of
            # Phase2bs for the leader's latest contiguous slot block).
            lo = int(slots.min())
            hi = int(slots.max())
            width = hi - lo + 1
            window = self.checker.window
            bucket = next((b for b in self.dense_buckets if b >= width),
                          None) if width <= self.max_dense else None
            if (bucket is not None
                    and slots.shape[0] >= width * self.min_fill
                    and lo % window + bucket <= window):
                block = np.zeros((self.checker.num_nodes, bucket),
                                 dtype=np.uint8)
                block[cols, slots - lo] = 1
                newly = self.checker.record_block_async(lo, block,
                                                        vote_round=dom)
                device_parts.append((np.arange(slots.shape[0]), newly,
                                     slots - lo))
                dispatch = (slots, rounds, device_parts)
                self._slots, self._cols, self._rounds = [], [], []
                self._ranges = []
                if self.pipelined:
                    self._inflight.append(dispatch)
                    return []
                return self.collect(dispatch)
            dense_idx = np.arange(slots.shape[0])
            pre = post = None
        else:
            round_values, round_counts = np.unique(rounds,
                                                   return_counts=True)
            dom = int(round_values[np.argmax(round_counts)])
            dense_idx = np.flatnonzero(rounds == dom)
            pre = np.flatnonzero(rounds < dom)
            post = np.flatnonzero(rounds > dom)
        if pre is not None and pre.size:
            self._dispatch_sparse(device_parts, slots, cols, rounds, pre)

        # Cluster the dominant round's slots into contiguous runs.
        ds = slots[dense_idx]
        if ds.size and np.all(ds[:-1] <= ds[1:]):  # arrival order is
            sidx = dense_idx                       # already slot-sorted
            ss = ds
        else:
            order = np.argsort(ds, kind="stable")
            sidx = dense_idx[order]
            ss = ds[order]
        window = self.checker.window
        sparse_leftover = []
        cluster_bounds = np.flatnonzero(np.diff(ss) >= self.max_dense) + 1
        for cluster in np.split(np.arange(sidx.size), cluster_bounds):
            cl = sidx[cluster]
            cs = ss[cluster]
            hi = int(cs[-1])
            width = hi - int(cs[0]) + 1
            if cl.size < width * self.min_fill:
                sparse_leftover.append(cl)
                continue
            # Chunk the run at bucket widths, breaking at the ring end
            # (record_block's no-straddle contract). Each chunk starts
            # at an actual member slot, so the loop is O(#chunks).
            i = 0
            while i < cs.size:
                start = int(cs[i])
                room = window - start % window
                remaining = hi - start + 1
                bucket = next((b for b in self.dense_buckets
                               if b >= min(remaining, self.max_dense)
                               and b <= room), None)
                if bucket is None:
                    bucket = max((b for b in self.dense_buckets
                                  if b <= room), default=None)
                    if bucket is None:  # < 64 columns to the ring end
                        j = int(np.searchsorted(cs, start + room))
                        sparse_leftover.append(cl[i:j])
                        i = j
                        continue
                j = int(np.searchsorted(cs, start + bucket))
                members = cl[i:j]
                block = np.zeros(
                    (self.checker.num_nodes, bucket), dtype=np.uint8)
                block[cols[members], slots[members] - start] = 1
                newly = self.checker.record_block_async(
                    start, block, vote_round=dom)
                # Device results stay at the padded bucket shape;
                # per-vote positions are applied host-side in collect()
                # (a device gather here would compile per distinct
                # length).
                device_parts.append((members, newly,
                                     slots[members] - start))
                i = j

        for cl in sparse_leftover:
            self._dispatch_sparse(device_parts, slots, cols, rounds, cl)
        if post is not None and post.size:
            self._dispatch_sparse(device_parts, slots, cols, rounds, post)

        dispatch = (slots, rounds, device_parts)
        self._slots, self._cols, self._rounds = [], [], []
        self._ranges = []
        if self.pipelined:
            self._inflight.append(dispatch)
            return []
        return self.collect(dispatch)

    def _dispatch_sparse(self, device_parts, slots, cols, rounds,
                         idx) -> None:
        """Scatter-path dispatch, chunked so only prewarmed widths run."""
        for at in range(0, idx.size, self.max_chunk):
            chunk = idx[at:at + self.max_chunk]
            device_parts.append((chunk,
                                 self.checker.record_and_check_async(
                                     slots[chunk], cols[chunk],
                                     rounds[chunk],
                                     pad_to=(64 if chunk.size <= 64
                                             else self.max_chunk)),
                                 np.arange(chunk.size)))

    def has_pending(self) -> bool:
        return bool(self._inflight)

    def take_dispatch(self):
        """Pop the oldest in-flight dispatch (None if empty); pass it to
        :meth:`collect`. Safe to call from a collector thread."""
        try:
            return self._inflight.popleft()
        except IndexError:
            return None

    def collect(self, dispatch) -> list[tuple[int, int]]:
        """Fetch a dispatch's results (blocking on the device if they
        are not done yet) and dedup per slot (keeping each slot's first
        reporting round in dispatch order, as the dict oracle does)."""
        drain_slots, drain_rounds, device_parts = dispatch
        hits = np.zeros(len(drain_slots), dtype=bool)
        for index, mask, positions in device_parts:
            hits[index] = np.asarray(mask)[positions]
        hit_idx = np.flatnonzero(hits)
        if hit_idx.size == 0:
            return []
        slots = np.asarray(drain_slots, dtype=np.int64)[hit_idx]
        _, first = np.unique(slots, return_index=True)
        sel = hit_idx[np.sort(first)]
        rounds = np.asarray(drain_rounds, dtype=np.int64)
        return list(zip(np.asarray(drain_slots, dtype=np.int64)[sel]
                        .tolist(), rounds[sel].tolist()))
