"""Pluggable Phase2b write-quorum tracking: host dict or TPU vote board.

The ProxyLeader's vote-collection loop (ProxyLeader.scala:217-258) is the
hottest code in the reference. Here it is a strategy interface with two
implementations:

  * ``DictQuorumTracker`` -- the reference's semantics verbatim: a dict
    keyed (slot, round) accumulating (group, acceptor) votes. The oracle.
  * ``TpuQuorumTracker`` -- votes buffered per event-loop drain, then one
    ``TpuQuorumChecker.record_and_check`` scatter + matmul per drain.
    Acceptor coordinates flatten to columns ``group * group_size + index``.
    In non-flexible mode only a slot's own group is ever messaged, so a
    universe-wide count >= f+1 threshold is exactly the per-group f+1
    quorum; in flexible mode the grid write-spec applies.

Both report each (slot, round)'s quorum exactly once.
"""

from __future__ import annotations

import abc

import numpy as np

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.quorums import QuorumSpec
from frankenpaxos_tpu.quorums.spec import ANY


class QuorumTracker(abc.ABC):
    """Tracks Phase2b votes; reports slots whose quorum completes."""

    @abc.abstractmethod
    def record(self, slot: int, round: int, group_index: int,
               acceptor_index: int) -> None:
        ...

    def record_range(self, slot_start: int, slot_end: int, round: int,
                     group_index: int, acceptor_index: int) -> None:
        """One acceptor's votes for slots [slot_start, slot_end) in one
        round (a Phase2bRange). Default: per-slot expansion."""
        for slot in range(slot_start, slot_end):
            self.record(slot, round, group_index, acceptor_index)

    def record_votes(self, slots, rounds, group_index: int,
                     acceptor_index: int) -> None:
        """One acceptor's votes for an ARBITRARY slot array (a packed
        Phase2bVotes from a fragmented drain). Default: per-slot
        expansion."""
        for slot, round in zip(slots.tolist(), rounds.tolist()):
            self.record(int(slot), int(round), group_index,
                        acceptor_index)

    @abc.abstractmethod
    def drain(self) -> list[tuple[int, int]]:
        """Flush buffered votes; return [(slot, round)] newly at quorum."""


class DictQuorumTracker(QuorumTracker):
    def __init__(self, config: MultiPaxosConfig):
        self.config = config
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        # (slot, round) -> set of (group, index); None once chosen.
        self.states: dict[tuple[int, int], set | None] = {}
        self._newly: list[tuple[int, int]] = []

    def record(self, slot, round, group_index, acceptor_index) -> None:
        key = (slot, round)
        votes = self.states.get(key)
        if votes is None and key in self.states:
            return  # already chosen (Done)
        if votes is None:
            votes = set()
            self.states[key] = votes
        votes.add((group_index, acceptor_index))
        if self.config.flexible:
            flat = {g * self._row_size + i for g, i in votes}
            if not self.grid.is_superset_of_write_quorum(flat):
                return
        else:
            if len(votes) < self.config.f + 1:
                return
        self.states[key] = None  # Done
        self._newly.append(key)

    def drain(self) -> list[tuple[int, int]]:
        newly, self._newly = self._newly, []
        return newly


class TpuQuorumTracker(QuorumTracker):
    """Two operating modes, chosen by ``pipelined``:

    **Synchronous (default).** Each drain whose dominant-round span is
    at least ``min_device_slots`` wide is decided by ONE stateless
    predicate matmul over the drain's ``[n, B]`` vote block
    (``TpuQuorumChecker.check_block``) -- no board state, no ring
    bookkeeping, cost flat in B. Votes below quorum after that check
    (quorums straddling drains) spill into a host tally (a
    ``DictQuorumTracker``, the oracle itself) -- SURVEY.md section 7's
    "overflow -> host-side spill path". Drains NARROWER than the
    threshold skip the device entirely and go straight to the host
    tally: a ~150us fixed device round-trip cannot beat ~0.6us/vote
    Python below ~100 slots, exactly the small-batch host fallback
    every accelerator framework keeps. The result: at trickle widths
    the tracker matches the dict oracle, and past the threshold the
    per-drain cost stays flat while the oracle's grows per vote.

    **Pipelined.** Every dense run goes through the stateful on-device
    vote board (``record_block``): the drain DISPATCHES asynchronously
    (returning []) and enqueues an in-flight record; the caller
    collects completed dispatches via :meth:`take_dispatch` +
    :meth:`collect` -- from a worker thread (ProxyLeader posts results
    back onto the event loop) or a flush timer. This hides the
    device-link latency behind the event loop -- essential when the
    accelerator sits across a high-RTT link -- at the cost of one
    dispatch of added choose latency; the board must see every vote
    because results are not available within the drain."""

    def __init__(self, config: MultiPaxosConfig, window: int = 1 << 20,
                 pipelined: bool = False, mesh=None,
                 min_device_slots: int = 0):
        import collections

        self.config = config
        self.pipelined = pipelined
        # In-flight dispatches: (slots, rounds, device per-vote masks).
        # append/popleft are GIL-atomic, so a collector thread may pop
        # while the event loop appends.
        self._inflight = collections.deque()
        self._row_size = len(config.acceptor_addresses[0])
        num_cols = config.num_acceptor_groups * self._row_size
        universe = tuple(range(num_cols))
        if config.flexible:
            spec = config.quorum_grid().write_spec().reindexed(universe)
        else:
            spec = QuorumSpec(
                masks=np.ones((1, num_cols), dtype=np.uint8),
                thresholds=np.array([config.f + 1], dtype=np.int32),
                combine=ANY,
                universe=universe,
            )
        # Lazy: keeps jax out of dict-backend role processes entirely
        # (it costs seconds of startup per process).
        from frankenpaxos_tpu.ops.quorum import TpuQuorumChecker

        # Sync mode never records on the vote board (stateless checks +
        # host spill), so don't allocate a full `window`-wide board
        # there -- just enough columns for the largest dense bucket.
        checker_window = window if pipelined else min(window, 4096)
        self.checker = TpuQuorumChecker(spec, window=checker_window,
                                        mesh=mesh)
        self._slots: list[int] = []
        self._cols: list[int] = []
        self._rounds: list[int] = []
        # Ranged votes (Phase2bRange): [(start, end, col, round)] --
        # O(1) Python per message, expanded vectorized at drain time.
        self._ranges: list[tuple[int, int, int, int]] = []
        # Packed array votes (Phase2bVotes): [(slots, col, rounds)] --
        # O(1) Python per message, arrays straight off the native
        # codec's unpack.
        self._array_votes: list = []
        # Exactly-once reporting across drains, vectorized. The board's
        # `chosen` bitmap provides this for board-recorded votes, but
        # the stateless check_block path never touches the board, so a
        # duplicate full-quorum drain (resent acks) would re-report. A
        # host-side dedup ring keyed slot % window (owner slot + round
        # per column, numpy fancy-indexed in collect()) restores the
        # dict oracle's contract with O(batch) numpy instead of
        # per-slot set ops. Like the vote board itself it forgets a
        # slot once the ring wraps past it -- covered by the same
        # "window > max slots in flight" invariant.
        self._dedup_slot = np.full(window, -1, dtype=np.int64)
        self._dedup_round = np.full(window, np.iinfo(np.int64).min,
                                    dtype=np.int64)
        self._frontier = -1
        self._host_gc_cap = max(1 << 16, 2 * window)
        # Kernel width buckets. Drains are chunked to these so ONLY the
        # prewarmed widths ever compile -- an unexpected width compiling
        # mid-run stalls the event loop for seconds over a remote device
        # link. Dense buckets go wide (a contiguous 4k-slot run is one
        # slice+matmul call); the sparse scatter tail stays narrow.
        self.max_chunk = 256
        self.dense_buckets = tuple(
            b for b in (64, 256, 1024, 4096) if b <= window)
        if not self.dense_buckets:
            raise ValueError(f"window must be >= 64 (got {window}): the "
                             f"smallest prewarmed dense kernel bucket is "
                             f"64 columns")
        self.max_dense = self.dense_buckets[-1]
        # A dominant-round cluster goes dense when it's at least this
        # filled; emptier clusters cost fewer device calls via scatter.
        self.min_fill = 0.25
        if min_device_slots <= 0:
            # Auto-calibrate the host/device routing threshold to the
            # backend. On a real local TPU a stateless check is tens of
            # microseconds -- engage it early. On the host-XLA CPU
            # control, the call itself is ~150us but its AMBIENT cost
            # on a small host is the real price (kernel execution and
            # thread-pool churn timeshare with the single-threaded
            # actor pipeline; measured ~2-4ms of surrounding-pipeline
            # slowdown per call on a 1-CPU box), so the device must
            # only engage when a drain carries enough votes to beat
            # that: ~1k slots.
            import jax

            platform = jax.devices()[0].platform
            min_device_slots = 96 if platform == "tpu" else 1024
        self.min_device_slots = min_device_slots
        # Host spill tally for the synchronous mode (narrow drains +
        # below-quorum residue of stateless checks): the dict oracle
        # itself, so cross-drain accumulation has one authority with
        # proven semantics.
        self._host = DictQuorumTracker(config)
        # Pre-compile every bucket at construction -- before client
        # traffic -- so the first real drains don't stall on XLA
        # compiles. The board paths (record_block / record_and_check)
        # only run in pipelined mode; prewarming them in sync mode
        # would pay startup compiles for kernels that never execute.
        # Board prewarm votes land at round -1 (below any real round),
        # and release() clears the touched columns (including the ring
        # owners the prewarm claimed).
        for width in self.dense_buckets:
            warm = np.zeros((self.checker.num_nodes, width),
                            dtype=np.uint8)
            warm[0, 0] = 1
            self.checker.check_block(warm)
            if pipelined:
                self.checker.record_block(0, warm, vote_round=-1)
        if pipelined:
            for width in (1, self.max_chunk):
                self.checker.record_and_check([0] * width, [0] * width,
                                              [-1] * width)
            self.checker.release(np.arange(self.max_dense))

    def record(self, slot, round, group_index, acceptor_index) -> None:
        self._slots.append(slot)
        self._cols.append(group_index * self._row_size + acceptor_index)
        self._rounds.append(round)

    def record_range(self, slot_start, slot_end, round, group_index,
                     acceptor_index) -> None:
        if slot_end <= slot_start:
            # Drop empties like record_votes does: an empty range as
            # ra[0] would seed rnd0/lo from a zero-vote entry and yield
            # hi = start - 1 in _drain_sync.
            return
        self._ranges.append((slot_start, slot_end,
                             group_index * self._row_size
                             + acceptor_index, round))

    def record_votes(self, slots, rounds, group_index,
                     acceptor_index) -> None:
        slots = np.asarray(slots, dtype=np.int64)
        if not slots.size:
            # Drop empties at the door: every drain path assumes
            # non-empty entries (round scans, frontier max, rounds[0]).
            return
        self._array_votes.append(
            (slots, group_index * self._row_size + acceptor_index,
             np.asarray(rounds, dtype=np.int32)))

    def drain(self) -> list[tuple[int, int]]:
        """At most a few device calls (usually one, often zero) per
        event-loop drain; see the class docstring for the two modes."""
        if not self._slots and not self._ranges \
                and not self._array_votes:
            return []
        if self.pipelined:
            return self._drain_pipelined()
        return self._drain_sync()

    # --- synchronous mode -------------------------------------------------

    def _drain_sync(self) -> list[tuple[int, int]]:
        """Stateless device check for wide single-round drains; host
        tally for narrow drains, off-round votes, and the below-quorum
        residue of device checks.

        Steady-state Phase2b streams cover contiguous slot runs in one
        round (Leader.scala:331-408 allocates slots contiguously) and a
        slot's whole write quorum lands in ONE drain (the ProxyLeader
        fans each Phase2a to its quorum in one pass; the acks coalesce
        back together), so the common drain is one ``check_block``
        matmul with an empty residue."""
        ranges, self._ranges = self._ranges, []
        av, self._array_votes = self._array_votes, []
        sl, self._slots = self._slots, []
        cl, self._cols = self._cols, []
        rl, self._rounds = self._rounds, []

        # Trickle drains (a serial client, quiescence dribbles): pure
        # Python straight into the host tally -- no numpy conversions,
        # no device. This is the regime where ANY fixed overhead is
        # visible per command. An explicit tiny min_device_slots (the
        # component benchmarks pin the device path on) lowers this
        # cutoff too.
        nvotes = (len(sl) + sum(e - s for s, e, _, _ in ranges)
                  + sum(s.size for s, _, _ in av))
        if nvotes < min(48, self.min_device_slots):
            row = self._row_size
            frontier = max(sl) if sl else -1
            for k in range(len(sl)):
                g, i = divmod(cl[k], row)
                self._host.record(sl[k], rl[k], g, i)
            if ranges:
                frontier = max(frontier,
                               max(e - 1 for _, e, _, _ in ranges))
            if av:
                frontier = max(frontier,
                               max(int(s.max()) for s, _, _ in av
                                   if s.size))
            self._spill_ranges(ranges)
            self._spill_arrays(av)
            self._note_frontier(frontier)
            return self._host_results()

        slots = np.asarray(sl, dtype=np.int64)
        cols = np.asarray(cl, dtype=np.int32)
        rounds = np.asarray(rl, dtype=np.int32)
        # Ranges as an [R, 4] array: strided workloads shred ranged
        # acks into many single-slot runs, so everything below must be
        # vectorized over R, not Python-per-range.
        ra = (np.asarray(ranges, dtype=np.int64) if ranges
              else np.empty((0, 4), dtype=np.int64))

        # Uniform-round test + slot span.
        uniform = True
        lo = hi = None
        if ranges:
            rnd0 = int(ra[0, 3])
            uniform = bool((ra[:, 3] == rnd0).all())
            lo = int(ra[:, 0].min())
            hi = int(ra[:, 1].max()) - 1
        elif av:
            rnd0 = int(av[0][2][0]) if av[0][2].size else 0
        else:
            rnd0 = int(rounds[0])
        for s_arr, _, r_arr in av:
            if not uniform or not s_arr.size:
                break
            if not (r_arr == rnd0).all():
                uniform = False
                break
            alo, ahi = int(s_arr.min()), int(s_arr.max())
            lo = alo if lo is None else min(lo, alo)
            hi = ahi if hi is None else max(hi, ahi)
        if uniform and slots.size:
            if not (rounds == rnd0).all():
                uniform = False
            else:
                slo = int(slots.min())
                shi = int(slots.max())
                lo = slo if lo is None else min(lo, slo)
                hi = shi if hi is None else max(hi, shi)
        if not uniform:
            # Mixed rounds: election churn, preemption -- rare and
            # thin. Spill everything to the host tally in arrival
            # order (preserving the oracle's old-round-before-new
            # reporting liveness).
            frontier = int(slots.max()) if slots.size else -1
            if ranges:
                frontier = max(frontier, int(ra[:, 1].max()) - 1)
            if av:
                frontier = max(frontier,
                               max(int(s.max()) for s, _, _ in av
                                   if s.size))
            self._spill_ranges(ranges)
            self._spill_arrays(av)
            self._spill_votes(slots, cols, rounds)
            self._note_frontier(frontier)
            return self._host_results()

        width = hi - lo + 1
        if width < self.min_device_slots:
            # Narrow drain: the fixed device round-trip loses to
            # per-vote Python here -- host tally.
            self._spill_ranges(ranges)
            self._spill_arrays(av)
            self._spill_votes(slots, cols, rounds)
            self._note_frontier(hi)
            return self._host_results()

        # Wide single-round drain: one stateless check per max_dense
        # segment of the span (usually exactly one). Only segments
        # containing votes are materialized, so a pathological sparse
        # span costs O(active segments), not O(span).
        out: list[tuple[int, int]] = []
        seg = self.max_dense
        # Single-slot runs (the strided-ack shape) fill vectorized;
        # only genuinely multi-slot runs take the per-range slice loop.
        single = ra[ra[:, 1] - ra[:, 0] == 1] if ranges else ra
        multi = ([r for r in ranges if r[1] - r[0] > 1]
                 if ranges and single.shape[0] != ra.shape[0] else [])
        active = set()
        if slots.size:
            active.update(np.unique((slots - lo) // seg).tolist())
        if single.shape[0]:
            active.update(np.unique((single[:, 0] - lo) // seg).tolist())
        for s, e, _, _ in multi:
            active.update(range((s - lo) // seg, (e - 1 - lo) // seg + 1))
        for s_arr, _, _ in av:
            if s_arr.size:
                active.update(np.unique((s_arr - lo) // seg).tolist())
        # Two phases: dispatch every segment's check first, THEN fetch
        # -- k segments pay one overlap-able round-trip, not k
        # serialized ones.
        dispatched = []
        for seg_idx in sorted(active):
            seg_start = lo + seg_idx * seg
            seg_end = min(seg_start + seg, hi + 1)
            seg_width = seg_end - seg_start
            bucket = next(b for b in self.dense_buckets
                          if b >= seg_width)
            block = np.zeros((self.checker.num_nodes, bucket),
                             dtype=np.uint8)
            if single.shape[0]:
                inseg = ((single[:, 0] >= seg_start)
                         & (single[:, 0] < seg_end))
                block[single[inseg, 2],
                      single[inseg, 0] - seg_start] = 1
            for s, e, col, _ in multi:
                cs, ce = max(s, seg_start), min(e, seg_end)
                if cs < ce:
                    block[col, cs - seg_start:ce - seg_start] = 1
            if slots.size:
                inseg = (slots >= seg_start) & (slots < seg_end)
                block[cols[inseg], slots[inseg] - seg_start] = 1
            for s_arr, col, _ in av:
                inseg = (s_arr >= seg_start) & (s_arr < seg_end)
                block[col, s_arr[inseg] - seg_start] = 1
            dispatched.append((seg_start, seg_width, block,
                               self.checker.check_block_async(block)))
        for seg_start, seg_width, block, mask in dispatched:
            hit = np.asarray(mask)[:seg_width]
            touched = block[:, :seg_width].any(axis=0)
            chosen = np.flatnonzero(hit & touched)
            if chosen.size:
                chosen_slots = seg_start + chosen.astype(np.int64)
                fresh = self._fresh_mask(chosen_slots, rnd0)
                out.extend(zip(chosen_slots[fresh].tolist(),
                               (rnd0,) * int(fresh.sum())))
            resid = touched & ~hit
            if resid.any():
                # Below-quorum residue: votes whose quorum straddles
                # drains. Spill to the host tally (few by
                # construction), which may complete earlier slots.
                rcols, rpos = np.nonzero(block[:, :seg_width]
                                         * resid[None, :])
                for col, pos in zip(rcols.tolist(), rpos.tolist()):
                    g, i = divmod(col, self._row_size)
                    self._host.record(seg_start + pos, rnd0, g, i)
        self._note_frontier(hi)
        out.extend(self._host_results())
        return out

    def _spill_votes(self, slots, cols, rounds) -> None:
        for k in range(slots.size):
            g, i = divmod(int(cols[k]), self._row_size)
            self._host.record(int(slots[k]), int(rounds[k]), g, i)

    def _spill_ranges(self, ranges) -> None:
        for s, e, col, r in ranges:
            g, i = divmod(col, self._row_size)
            for slot in range(s, e):
                self._host.record(slot, r, g, i)

    def _spill_arrays(self, array_votes) -> None:
        for s_arr, col, r_arr in array_votes:
            g, i = divmod(col, self._row_size)
            for slot, r in zip(s_arr.tolist(), r_arr.tolist()):
                self._host.record(slot, r, g, i)

    def _note_frontier(self, max_slot: int) -> None:
        """Bound the host tally: the oracle's states dict never evicts,
        which is fine for the oracle (parity with the reference's
        per-slot maps) but the spill tally must not grow for the life
        of the process. Once it exceeds the cap, prune entries the
        dedup ring has forgotten anyway (slot < frontier - ring size)
        -- the same windowed-staleness contract as the vote board's
        self-reclaiming ring."""
        if max_slot > self._frontier:
            self._frontier = max_slot
        if len(self._host.states) > self._host_gc_cap:
            cutoff = self._frontier - self._dedup_slot.shape[0]
            self._host.states = {
                k: v for k, v in self._host.states.items()
                if k[0] >= cutoff}

    def _host_results(self) -> list[tuple[int, int]]:
        """Drain the host tally, marking its completions in the dedup
        ring so a later stateless re-ack of the same slot is not
        re-reported."""
        results = self._host.drain()
        if not results:
            return []
        if len(results) <= 8:  # scalar ring ops beat array setup here
            n = self._dedup_slot.shape[0]
            out = []
            seen: set[int] = set()
            for slot, rnd in results:
                if slot in seen:
                    # Mixed-round churn can complete one slot at two
                    # rounds in one drain; keep the first (oldest
                    # round, arrival order) so the ring holds exactly
                    # one (slot, round) pair per slot.
                    continue
                seen.add(slot)
                i = slot % n
                if (self._dedup_slot[i] != slot
                        or self._dedup_round[i] != rnd):
                    self._dedup_slot[i] = slot
                    self._dedup_round[i] = rnd
                    out.append((slot, rnd))
            return out
        slots = np.asarray([s for s, _ in results], dtype=np.int64)
        rounds = np.asarray([r for _, r in results], dtype=np.int64)
        # _fresh_mask requires unique slots (its last-wins fancy-indexed
        # ring write forgets one pair otherwise, re-reporting a later
        # duplicate re-ack): dedup to one entry per slot, keeping the
        # first = oldest-round arrival, as the dict oracle reports.
        # The DROPPED (slot, newer-round) pair is never reported -- a
        # later re-ack completing it would be its FIRST report, which
        # the per-(slot, round) exactly-once contract permits (the
        # ring can only remember one round per slot).
        uniq, first = np.unique(slots, return_index=True)
        if uniq.size != slots.size:
            first.sort()
            slots = slots[first]
            rounds = rounds[first]
            results = [results[i] for i in first.tolist()]
        fresh = self._fresh_mask(slots, rounds)
        if fresh.all():
            return results
        return [kv for kv, f in zip(results, fresh.tolist()) if f]

    # --- pipelined mode ---------------------------------------------------

    def _drain_pipelined(self) -> list[tuple[int, int]]:
        """Dispatch this drain's votes onto the stateful vote board
        asynchronously; results are collected later (take_dispatch +
        collect). Sparse stragglers and off-round votes go through the
        scatter path; votes in rounds OLDER than the dominant round
        dispatch BEFORE the dense block so an old-round quorum
        completing in this drain is reported before the newer round's
        preemption clears it."""
        parts: list[tuple] = []
        slots = np.asarray(self._slots, dtype=np.int64)
        cols = np.asarray(self._cols, dtype=np.int32)
        rounds = np.asarray(self._rounds, dtype=np.int32)
        if self._ranges or self._array_votes:
            # Expand ranged/packed votes vectorized (the whole point of
            # Phase2bRange/Phase2bVotes: no per-slot Python before this
            # point).
            parts_s = [slots] if slots.size else []
            parts_c = [cols] if slots.size else []
            parts_r = [rounds] if slots.size else []
            for start, end, col, rnd in self._ranges:
                width = end - start
                parts_s.append(np.arange(start, end, dtype=np.int64))
                parts_c.append(np.full(width, col, dtype=np.int32))
                parts_r.append(np.full(width, rnd, dtype=np.int32))
            for s_arr, col, r_arr in self._array_votes:
                parts_s.append(s_arr)
                parts_c.append(np.full(s_arr.size, col, dtype=np.int32))
                parts_r.append(r_arr)
            slots = np.concatenate(parts_s)
            cols = np.concatenate(parts_c)
            rounds = np.concatenate(parts_r)

        # The drain's dominant round (fast path: single-round drain).
        if rounds[0] == rounds[-1] and (rounds == rounds[0]).all():
            dom = int(rounds[0])
            # Single-round drain within one dense bucket: one block.
            lo = int(slots.min())
            hi = int(slots.max())
            width = hi - lo + 1
            bucket = next((b for b in self.dense_buckets if b >= width),
                          None) if width <= self.max_dense else None
            if (bucket is not None
                    and slots.shape[0] >= width * self.min_fill):
                block = np.zeros((self.checker.num_nodes, bucket),
                                 dtype=np.uint8)
                block[cols, slots - lo] = 1
                self._record_board(parts, lo, block, bucket, dom)
                self._slots, self._cols, self._rounds = [], [], []
                self._ranges = []
                self._array_votes = []
                self._inflight.append(parts)
                return []
            dense_idx = np.arange(slots.shape[0])
            pre = post = None
        else:
            round_values, round_counts = np.unique(rounds,
                                                   return_counts=True)
            dom = int(round_values[np.argmax(round_counts)])
            dense_idx = np.flatnonzero(rounds == dom)
            pre = np.flatnonzero(rounds < dom)
            post = np.flatnonzero(rounds > dom)
        if pre is not None and pre.size:
            self._dispatch_sparse(parts, slots, cols, rounds, pre)

        # Cluster the dominant round's slots into contiguous runs.
        ds = slots[dense_idx]
        if ds.size and np.all(ds[:-1] <= ds[1:]):  # arrival order is
            sidx = dense_idx                       # already slot-sorted
            ss = ds
        else:
            order = np.argsort(ds, kind="stable")
            sidx = dense_idx[order]
            ss = ds[order]
        sparse_leftover = []
        cluster_bounds = np.flatnonzero(np.diff(ss) >= self.max_dense) + 1
        for cluster in np.split(np.arange(sidx.size), cluster_bounds):
            cl = sidx[cluster]
            cs = ss[cluster]
            hi = int(cs[-1])
            width = hi - int(cs[0]) + 1
            if cl.size < width * self.min_fill:
                sparse_leftover.append(cl)
                continue
            # Chunk the run at prewarmed bucket widths. Each chunk
            # starts at an actual member slot, so the loop is
            # O(#chunks).
            i = 0
            while i < cs.size:
                start = int(cs[i])
                remaining = hi - start + 1
                bucket = next((b for b in self.dense_buckets
                               if b >= min(remaining, self.max_dense)))
                j = int(np.searchsorted(cs, start + bucket))
                members = cl[i:j]
                block = np.zeros(
                    (self.checker.num_nodes, bucket), dtype=np.uint8)
                block[cols[members], slots[members] - start] = 1
                self._record_board(parts, start, block, bucket, dom)
                i = j

        for cl in sparse_leftover:
            self._dispatch_sparse(parts, slots, cols, rounds, cl)
        if post is not None and post.size:
            self._dispatch_sparse(parts, slots, cols, rounds, post)

        self._slots, self._cols, self._rounds = [], [], []
        self._ranges = []
        self._array_votes = []
        self._inflight.append(parts)
        return []

    def _record_board(self, parts: list, start: int, block: np.ndarray,
                      bucket: int, rnd: int) -> None:
        """Record a dense run on the vote board, splitting at the ring
        end (record_block's no-straddle contract)."""
        window = self.checker.window
        room = window - start % window
        if bucket <= room:
            newly = self.checker.record_block_async(start, block,
                                                    vote_round=rnd)
            parts.append(("block", start, bucket, rnd, newly))
        else:
            self._record_board_split(parts, start, block, room, rnd)

    def _record_board_split(self, parts: list, start: int,
                            block: np.ndarray, room: int,
                            rnd: int) -> None:
        """Record a block that straddles the ring end WITHOUT compiling
        any new kernel width: each piece is decomposed into prewarmed
        bucket widths, and sub-bucket remainders take the (prewarmed)
        scatter path. A mid-run XLA compile would stall the event loop
        for seconds over a remote device link."""
        self._record_board_bucketed(parts, start, block[:, :room], rnd)
        rest = block[:, room:]
        if rest.any():
            self._record_board_bucketed(parts, start + room,
                                        np.ascontiguousarray(rest), rnd)

    def _record_board_bucketed(self, parts: list, start: int,
                               block: np.ndarray, rnd: int) -> None:
        width = block.shape[1]
        i = 0
        while i < width:
            bucket = next((b for b in reversed(self.dense_buckets)
                           if b <= width - i), None)
            if bucket is None:
                # Remainder narrower than the smallest bucket: scatter.
                rows, pos = np.nonzero(block[:, i:])
                if rows.size:
                    self._dispatch_sparse(
                        parts, (start + i + pos).astype(np.int64),
                        rows.astype(np.int32),
                        np.full(rows.size, rnd, dtype=np.int32),
                        np.arange(rows.size))
                return
            sub = block[:, i:i + bucket]
            if sub.any():
                newly = self.checker.record_block_async(
                    start + i, np.ascontiguousarray(sub), vote_round=rnd)
                parts.append(("block", start + i, bucket, rnd, newly))
            i += bucket

    def _dispatch_sparse(self, parts, slots, cols, rounds, idx) -> None:
        """Scatter-path dispatch, chunked so only prewarmed widths run."""
        for at in range(0, idx.size, self.max_chunk):
            chunk = idx[at:at + self.max_chunk]
            parts.append(("votes", slots[chunk], rounds[chunk],
                          self.checker.record_and_check_async(
                              slots[chunk], cols[chunk], rounds[chunk],
                              pad_to=(64 if chunk.size <= 64
                                      else self.max_chunk)),
                          chunk.size))

    def has_pending(self) -> bool:
        return bool(self._inflight)

    def take_dispatch(self):
        """Pop the oldest in-flight dispatch (None if empty); pass it to
        :meth:`collect`. Safe to call from a collector thread."""
        try:
            return self._inflight.popleft()
        except IndexError:
            return None

    def collect(self, dispatch) -> list[tuple[int, int]]:
        """Fetch a dispatch's results (blocking on the device for any
        part not done yet) and dedup per slot, keeping each slot's
        first reporting round in part order (as the dict oracle's
        arrival-order reporting does).

        Parts come in two shapes: ``("block", start, width, round,
        device_mask)`` -- a per-slot newly-chosen mask from the board;
        ``("votes", slots, rounds, device_mask, n)`` -- a per-vote mask
        from the scatter path."""
        out: list[tuple[int, int]] = []
        for part in dispatch:
            kind = part[0]
            if kind == "block":
                _, start, width, rnd, mask = part
                m = np.asarray(mask)[:width]
                slots = start + np.flatnonzero(m).astype(np.int64)
                if slots.size:
                    fresh = self._fresh_mask(slots, rnd)
                    out.extend(zip(slots[fresh].tolist(),
                                   (rnd,) * int(fresh.sum())))
            else:  # "votes"
                _, vslots, vrounds, mask, n = part
                m = np.asarray(mask)[:n]
                hit = np.flatnonzero(m)
                if hit.size:
                    # Dedup duplicate slots within the part (keep the
                    # first, as the per-vote mask reports per vote).
                    hslots = np.asarray(vslots, dtype=np.int64)[hit]
                    _, first = np.unique(hslots, return_index=True)
                    sel = hit[np.sort(first)]
                    slots = np.asarray(vslots, dtype=np.int64)[sel]
                    rounds = np.asarray(vrounds, dtype=np.int64)[sel]
                    fresh = self._fresh_mask(slots, rounds)
                    out.extend(zip(slots[fresh].tolist(),
                                   rounds[fresh].tolist()))
        return out

    def _fresh_mask(self, slots: np.ndarray, rounds) -> np.ndarray:
        """Vectorized exactly-once filter: True where (slot, round) has
        not been reported before (within the dedup ring's memory);
        marks the fresh ones reported. ``slots`` must be unique within
        the call."""
        idx = slots % self._dedup_slot.shape[0]
        dup = (self._dedup_slot[idx] == slots) \
            & (self._dedup_round[idx] == rounds)
        fresh = ~dup
        fi = idx[fresh]
        self._dedup_slot[fi] = slots[fresh]
        self._dedup_round[fi] = np.asarray(rounds)[fresh] \
            if isinstance(rounds, np.ndarray) else rounds
        return fresh
