"""MultiPaxos Batcher: accumulate client writes into batches for the
leader.

Reference behavior: multipaxos/Batcher.scala:67-190. Client requests
append to a growing batch; at ``batch_size`` the batch goes to the
current round's leader. A NotLeaderBatcher bounce stashes the batch and
asks every leader who leads (LeaderInfoRequestBatcher); the reply updates
the round and flushes stashed batches.
"""

from __future__ import annotations

import dataclasses

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ClientRequest,
    ClientRequestBatch,
    Command,
    CommandBatch,
    LeaderInfoReplyBatcher,
    LeaderInfoRequestBatcher,
    NotLeaderBatcher,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class BatcherOptions:
    batch_size: int = 100
    # Flush a PARTIAL batch after this long (0 disables). The reference
    # only flushes on batch_size (Batcher.scala:100-135), which assumes
    # offered load >> batch_size; under a closed-loop trickle a partial
    # batch would otherwise strand its commands (and the client loops
    # waiting on them) forever.
    flush_period_s: float = 0.05
    measure_latencies: bool = True


class Batcher(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 options: BatcherOptions = BatcherOptions(),
                 collectors: Collectors | None = None):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check_ge(options.batch_size, 1)
        self.config = config
        self.options = options
        collectors = collectors or FakeCollectors()
        self.metrics_latency = collectors.summary(
            "multipaxos_batcher_requests_latency_seconds", labels=("type",))
        self.metrics_batches = collectors.counter(
            "multipaxos_batcher_batches_sent_total")
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.round = 0
        self.growing_batch: list[Command] = []
        self.pending_resend_batches: list[ClientRequestBatch] = []
        self._flush_timer = None
        if options.flush_period_s > 0:
            self._flush_timer = self.timer(
                "batchFlush", options.flush_period_s, self._flush_partial)

    def _leader_address(self) -> Address:
        return self.config.leader_addresses[self.round_system.leader(
            self.round)]

    def _flush_partial(self) -> None:
        # One-shot: re-armed by _handle_client_request when the next
        # batch starts growing.
        if self.growing_batch:
            self._send_batch()

    def _send_batch(self) -> None:
        self.send(self._leader_address(), ClientRequestBatch(
            CommandBatch(tuple(self.growing_batch))))
        self.growing_batch.clear()
        self.metrics_batches.inc()

    def receive(self, src: Address, message) -> None:
        # timed(label) handler latency summaries (Leader.scala:281-293).
        if self.options.measure_latencies:
            with self.metrics_latency.labels(
                    type(message).__name__).time():
                self._receive_impl(src, message)
        else:
            self._receive_impl(src, message)

    def _receive_impl(self, src: Address, message) -> None:
        if isinstance(message, ClientRequest):
            self._handle_client_request(src, message)
        elif isinstance(message, NotLeaderBatcher):
            self._handle_not_leader(src, message)
        elif isinstance(message, LeaderInfoReplyBatcher):
            self._handle_leader_info(src, message)
        else:
            self.logger.fatal(f"unexpected batcher message {message!r}")

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        self.growing_batch.append(request.command)
        if len(self.growing_batch) >= self.options.batch_size:
            self._send_batch()
        elif self._flush_timer is not None \
                and len(self.growing_batch) == 1:
            # Arm the partial-batch flush when a batch starts growing.
            self._flush_timer.stop()
            self._flush_timer.start()

    def _handle_not_leader(self, src: Address,
                           bounce: NotLeaderBatcher) -> None:
        self.pending_resend_batches.append(bounce.client_request_batch)
        for leader in self.config.leader_addresses:
            self.send(leader, LeaderInfoRequestBatcher())

    def _handle_leader_info(self, src: Address,
                            reply: LeaderInfoReplyBatcher) -> None:
        if reply.round <= self.round and self.pending_resend_batches:
            # Stale info, but we still owe resends once a new round shows.
            pass
        if reply.round > self.round:
            self.round = reply.round
        for batch in self.pending_resend_batches:
            self.send(self._leader_address(), batch)
        self.pending_resend_batches.clear()
