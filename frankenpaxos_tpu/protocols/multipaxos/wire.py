"""Fixed-layout binary codecs for the MultiPaxos hot-path messages.

The reference's every message is a protobuf with a per-role oneof
envelope (ProtoSerializer.scala:3-11, multipaxos/MultiPaxos.proto:
489-588). Here the hot-path messages -- the ones a steady-state write
touches: ClientRequest -> Phase2a -> Phase2b -> Chosen -> ClientReply,
plus the gossip/watermark traffic around them -- get hand-laid-out
binary codecs registered with the runtime's HybridSerializer (see
runtime/serializer.py); cold-path messages (Phase1*, reads,
reconfiguration) stay pickled. Layouts are little-endian fixed-width
structs with length-prefixed strings/bytes: decodable from any
language, no code execution on decode, and several times faster than
pickling dataclasses.

Importing this module (protocols.multipaxos does) registers the codecs
process-wide; both sides of every channel share the schema.
"""

from __future__ import annotations

import dataclasses
import struct

from frankenpaxos_tpu.protocols.multipaxos.messages import (
    BatchMaxSlotReply,
    BatchMaxSlotRequest,
    Chosen,
    ChosenRun,
    ChosenWatermark,
    ClientReply,
    ClientReplyArray,
    ClientReplyBatch,
    ClientRequest,
    ClientRequestArray,
    ClientRequestBatch,
    Command,
    CommandBatch,
    CommandId,
    EventualReadRequest,
    EventualReadRequestBatch,
    LeaderInfoReplyBatcher,
    LeaderInfoReplyClient,
    LeaderInfoRequestBatcher,
    LeaderInfoRequestClient,
    MaxSlotReply,
    MaxSlotRequest,
    Nack,
    NOOP,
    Noop,
    NotLeaderBatcher,
    NotLeaderClient,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2aRun,
    Phase2b,
    Phase2bRange,
    Phase2bVotes,
    ReadReply,
    ReadReplyBatch,
    ReadRequest,
    ReadRequestBatch,
    Recover,
    SequentialReadRequest,
    SequentialReadRequestBatch,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_I32 = struct.Struct("<i")
_QI = struct.Struct("<qi")
_QQII = struct.Struct("<qqii")


def _put_bytes(out: bytearray, data: bytes) -> None:
    out += _I32.pack(len(data))
    out += data


def _take_bytes(buf: bytes, at: int) -> tuple[bytes, int]:
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    return buf[at:at + n], at + n


def _put_address(out: bytearray, address) -> None:
    """Addresses are (host, port) tuples on TCP, plain strings in sims;
    anything else (exotic sim addresses) rides a pickled escape hatch."""
    if (isinstance(address, tuple) and len(address) == 2
            and isinstance(address[0], str)
            and isinstance(address[1], int)):
        host, port = address
        out.append(1)
        _put_bytes(out, host.encode())
        out += _I32.pack(port)
    elif isinstance(address, str):
        out.append(0)
        _put_bytes(out, address.encode())
    else:
        from frankenpaxos_tpu.runtime import serializer

        out.append(2)
        _put_bytes(out, serializer.guarded_pickle_dumps(address, "address"))


def _take_address(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    raw, at = _take_bytes(buf, at)
    if kind == 1:
        (port,) = _I32.unpack_from(buf, at)
        return (raw.decode(), port), at + 4
    if kind == 2:
        from frankenpaxos_tpu.runtime import serializer

        return serializer.guarded_pickle_loads(raw, "address"), at
    return raw.decode(), at


def _put_cid(out: bytearray, cid: CommandId) -> None:
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)


def _take_cid(buf: bytes, at: int) -> tuple[CommandId, int]:
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    return CommandId(address, pseudonym, id), at + 16


def _put_command(out: bytearray, command: Command) -> None:
    _put_cid(out, command.command_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int) -> tuple[Command, int]:
    cid, at = _take_cid(buf, at)
    payload, at = _take_bytes(buf, at)
    return Command(cid, payload), at


def _put_value(out: bytearray, value) -> None:
    """CommandBatchOrNoop."""
    if isinstance(value, Noop):
        out.append(0)
        return
    out.append(1)
    out += _I32.pack(len(value.commands))
    for command in value.commands:
        _put_command(out, command)


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return NOOP, at
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    commands = []
    for _ in range(n):
        command, at = _take_command(buf, at)
        commands.append(command)
    return CommandBatch(tuple(commands)), at


def encode_value(value) -> bytes:
    """One CommandBatchOrNoop as a standalone byte segment (the WAL's
    WalVote payload; same layout Phase2a carries on the wire)."""
    out = bytearray()
    _put_value(out, value)
    return bytes(out)


def decode_value(data: bytes):
    value, _ = _take_value(data, 0)
    return value


def encode_value_array(values) -> bytes:
    """A value array as a standalone byte segment (the WAL's
    WalVoteRun/WalChosenRun payload). Encoding a LazyValueArray -- the
    form runs arrive in -- is a raw copy: logging a drain's Phase2aRun
    never re-materializes its values."""
    out = bytearray()
    _put_value_array(out, values)
    return bytes(out)


def decode_value_array(data: bytes) -> LazyValueArray:
    values, _ = _take_value_array(data, 0)
    return values


class Phase2bCodec(MessageCodec):
    """The single hottest message (2f+1 per slot)."""

    message_type = Phase2b
    tag = 1

    def encode(self, out, message):
        out += _QQII.pack(message.slot, message.round,
                          message.group_index, message.acceptor_index)

    def decode(self, buf, at):
        slot, round, group, acceptor = _QQII.unpack_from(buf, at)
        return Phase2b(group_index=group, acceptor_index=acceptor,
                       slot=slot, round=round), at + 24


class Phase2aCodec(MessageCodec):
    message_type = Phase2a
    tag = 2

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return Phase2a(slot=slot, round=round, value=value), at


class ChosenCodec(MessageCodec):
    message_type = Chosen
    tag = 3

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return Chosen(slot=slot, value=value), at


class ClientRequestCodec(MessageCodec):
    message_type = ClientRequest
    tag = 4

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return ClientRequest(command), at


class ClientRequestBatchCodec(MessageCodec):
    message_type = ClientRequestBatch
    tag = 5

    def encode(self, out, message):
        _put_value(out, message.batch)

    def decode(self, buf, at):
        batch, at = _take_value(buf, at)
        return ClientRequestBatch(batch), at


class ClientReplyCodec(MessageCodec):
    message_type = ClientReply
    tag = 6

    def encode(self, out, message):
        _put_reply(out, message)

    def decode(self, buf, at):
        return _take_reply(buf, at, ClientReply)


class ChosenWatermarkCodec(MessageCodec):
    message_type = ChosenWatermark
    tag = 7

    def encode(self, out, message):
        out += _I64.pack(message.slot)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        return ChosenWatermark(slot=slot), at + 8


_P2BR = struct.Struct("<qqqii")  # start, end, round, group, acceptor


class Phase2bRangeCodec(MessageCodec):
    message_type = Phase2bRange
    tag = 13

    def encode(self, out, message):
        out += _P2BR.pack(message.slot_start_inclusive,
                          message.slot_end_exclusive, message.round,
                          message.group_index, message.acceptor_index)

    def decode(self, buf, at):
        start, end, round, group, acceptor = _P2BR.unpack_from(buf, at)
        return Phase2bRange(group_index=group, acceptor_index=acceptor,
                            slot_start_inclusive=start,
                            slot_end_exclusive=end,
                            round=round), at + _P2BR.size


class Phase2bVotesCodec(MessageCodec):
    message_type = Phase2bVotes
    # 114: payload records widened from (i32 slot, i32 round) to
    # (i64 slot, i32 round). The tag bump makes any decoder that only
    # knows the 8-byte layout drop the frame loudly (unknown tag)
    # instead of silently mis-decoding 12-byte records.
    tag = 114

    def encode(self, out, message):
        out += _I32.pack(message.group_index)
        out += _I32.pack(message.acceptor_index)
        _put_bytes(out, message.packed)

    def decode(self, buf, at):
        (group,) = _I32.unpack_from(buf, at)
        (acceptor,) = _I32.unpack_from(buf, at + 4)
        packed, at = _take_bytes(buf, at + 8)
        # Validate the packed payload's count against its length HERE,
        # inside decode, so a malformed/hostile payload raises in the
        # transport's corrupt-frame guard (clean log-and-drop) instead
        # of inside the ProxyLeader's handler -- and before
        # unpack_votes2 sizes any allocation by the claimed count.
        from frankenpaxos_tpu import native

        native.check_votes2(packed)
        return Phase2bVotes(group_index=group, acceptor_index=acceptor,
                            packed=packed), at


# --- run-pipeline array codecs ---------------------------------------------
# Structure-of-arrays layouts: client addresses are hoisted into a
# per-message dedup TABLE and commands reference them by index, so a
# 1024-command run encodes its (usually one) client address once, not
# 1024 times. Address encode/decode was the dominant per-command
# serialization cost in the AoS form. Decoding yields a
# LazyValueArray: hot-path consumers that only forward or store the
# values (ProxyLeader, Acceptor) never materialize Command objects --
# re-encoding a lazy array is a raw bytes copy.

_CMD_ENTRY = struct.Struct("<iqq")  # address index, pseudonym, client id


class LazyValueArray:
    """Decode-on-demand view over an encoded value array segment.

    Iteration/indexing (Replica execution, Phase1b recovery) decodes
    the whole segment once and caches it; forwarding (ProxyLeader ->
    acceptors, ChosenRun emission of a full run) re-encodes by copying
    ``raw`` without ever parsing it."""

    __slots__ = ("raw", "n", "_values")

    def __init__(self, raw: bytes, n: int):
        self.raw = raw
        self.n = n
        self._values = None

    def _decode(self) -> tuple:
        if self._values is None:
            try:
                self._values = _parse_value_array(self.raw, 0, self.n)[0]
            except (struct.error, IndexError, KeyError,
                    UnicodeDecodeError, OverflowError, MemoryError) as e:
                # The lazy twin of HybridSerializer.from_bytes'
                # containment normalization: corruption surfacing at
                # first ACCESS still comes out as ValueError.
                raise ValueError(
                    f"corrupt value array (n={self.n}): {e}") from e
        return self._values

    def __len__(self) -> int:
        return self.n

    def __iter__(self):
        return iter(self._decode())

    def __getitem__(self, i):
        return self._decode()[i]

    def __eq__(self, other):
        if isinstance(other, LazyValueArray):
            return self._decode() == other._decode()
        if isinstance(other, tuple):
            return self._decode() == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"LazyValueArray(n={self.n})"


def _put_value_array(out: bytearray, values) -> None:
    """count + byte length + [address table | per-value body]. The byte
    length lets decode wrap the segment lazily without parsing it."""
    if isinstance(values, LazyValueArray):
        out += _I32.pack(values.n)
        out += _I32.pack(len(values.raw))
        out += values.raw
        return
    table: dict = {}
    table_bytes = bytearray()
    body = bytearray()
    for value in values:
        if isinstance(value, Noop):
            body.append(0)
            continue
        body.append(1)
        body += _I32.pack(len(value.commands))
        for command in value.commands:
            cid = command.command_id
            idx = table.get(cid.client_address)
            if idx is None:
                idx = len(table)
                table[cid.client_address] = idx
                _put_address(table_bytes, cid.client_address)
            body += _CMD_ENTRY.pack(idx, cid.client_pseudonym,
                                    cid.client_id)
            _put_bytes(body, command.command)
    out += _I32.pack(len(values))
    out += _I32.pack(4 + len(table_bytes) + len(body))
    out += _I32.pack(len(table))
    out += table_bytes
    out += body


_I32I32 = struct.Struct("<ii")


def _take_value_array(buf: bytes, at: int) -> tuple:
    """-> (LazyValueArray, next offset).

    The count and byte length are validated HERE, inside codec decode,
    so a hostile frame claiming 2^30 values raises in the transport's
    corrupt-frame guard before any consumer sizes an allocation by the
    count (every value costs >= 1 body byte, so n is bounded by the
    actual payload). CONTENT parsing stays deferred: a length-valid but
    content-corrupt segment surfaces as ValueError at first access in
    the consuming actor -- the same trust level as the pickled cold
    path in this single-trust-domain deployment model."""
    n, nbytes = _I32I32.unpack_from(buf, at)
    at += 8
    if n < 0 or nbytes < 4 or at + nbytes > len(buf) or n + 4 > nbytes:
        raise ValueError(
            f"malformed value array: count {n} / length {nbytes} "
            f"exceed payload ({len(buf) - at} bytes left)")
    return LazyValueArray(buf[at:at + nbytes], n), at + nbytes


def _parse_value_array(buf: bytes, at: int, n: int) -> tuple:
    (t,) = _I32.unpack_from(buf, at)
    at += 4
    addresses = []
    for _ in range(t):
        address, at = _take_address(buf, at)
        addresses.append(address)
    values = []
    for _ in range(n):
        kind = buf[at]
        at += 1
        if kind == 0:
            values.append(NOOP)
            continue
        (k,) = _I32.unpack_from(buf, at)
        at += 4
        commands = []
        for _ in range(k):
            idx, pseudonym, id = _CMD_ENTRY.unpack_from(buf, at)
            payload, at = _take_bytes(buf, at + 20)
            commands.append(Command(
                CommandId(addresses[idx], pseudonym, id), payload))
        values.append(CommandBatch(tuple(commands)))
    return tuple(values), at


class ClientRequestArrayCodec(MessageCodec):
    """All commands in one array come from ONE client by construction
    (the client stages its own writes), so the address is encoded once
    for the whole message."""

    message_type = ClientRequestArray
    tag = 115

    def encode(self, out, message):
        _put_address(out, message.commands[0].command_id.client_address)
        out += _I32.pack(len(message.commands))
        for command in message.commands:
            cid = command.command_id
            out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
            _put_bytes(out, command.command)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        commands = []
        for _ in range(n):
            pseudonym, id = _I64I64.unpack_from(buf, at)
            payload, at = _take_bytes(buf, at + 16)
            commands.append(Command(
                CommandId(address, pseudonym, id), payload))
        return ClientRequestArray(commands=tuple(commands)), at


class Phase2aRunCodec(MessageCodec):
    message_type = Phase2aRun
    tag = 116

    def encode(self, out, message):
        out += _I64I64.pack(message.start_slot, message.round)
        _put_value_array(out, message.values)

    def decode(self, buf, at):
        start, round = _I64I64.unpack_from(buf, at)
        values, at = _take_value_array(buf, at + 16)
        return Phase2aRun(start_slot=start, round=round,
                          values=values), at


class ChosenRunCodec(MessageCodec):
    message_type = ChosenRun
    tag = 117

    def encode(self, out, message):
        out += _I64.pack(message.start_slot)
        _put_value_array(out, message.values)

    def decode(self, buf, at):
        (start,) = _I64.unpack_from(buf, at)
        values, at = _take_value_array(buf, at + 8)
        return ChosenRun(start_slot=start, values=values), at


_REPLY_ENTRY = struct.Struct("<qqq")  # pseudonym, client_id, slot


class ClientReplyArrayCodec(MessageCodec):
    message_type = ClientReplyArray
    tag = 118

    def encode(self, out, message):
        out += _I32.pack(len(message.entries))
        for pseudonym, client_id, slot, result in message.entries:
            out += _REPLY_ENTRY.pack(pseudonym, client_id, slot)
            _put_bytes(out, result)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        entries = []
        for _ in range(n):
            pseudonym, client_id, slot = _REPLY_ENTRY.unpack_from(buf, at)
            result, at = _take_bytes(buf, at + 24)
            entries.append((pseudonym, client_id, slot, result))
        return ClientReplyArray(entries=tuple(entries)), at


# --- read-path codecs -------------------------------------------------------
# The read hot path (the Evelyn read-scale mechanism): MaxSlotRequest ->
# MaxSlotReply quorum, then a Read*Request to one replica answered with
# a ReadReplyBatch. These carry every benchmarked read, so they get
# fixed layouts like the write path; the read-BATCHER shapes
# (ReadRequestBatch et al.) stay pickled until a deployment exercises
# them (grandfathered under COD301 in .paxlint-baseline.json).


class MaxSlotRequestCodec(MessageCodec):
    message_type = MaxSlotRequest
    tag = 119

    def encode(self, out, message):
        _put_cid(out, message.command_id)

    def decode(self, buf, at):
        cid, at = _take_cid(buf, at)
        return MaxSlotRequest(command_id=cid), at


_IIQ = struct.Struct("<iiq")


class MaxSlotReplyCodec(MessageCodec):
    message_type = MaxSlotReply
    tag = 120

    def encode(self, out, message):
        _put_cid(out, message.command_id)
        out += _IIQ.pack(message.group_index, message.acceptor_index,
                         message.slot)

    def decode(self, buf, at):
        cid, at = _take_cid(buf, at)
        group, acceptor, slot = _IIQ.unpack_from(buf, at)
        return MaxSlotReply(command_id=cid, group_index=group,
                            acceptor_index=acceptor,
                            slot=slot), at + _IIQ.size


class _SlotCommandCodec(MessageCodec):
    """Shared layout for the (slot, command) read requests."""

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_command(out, message.command)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        command, at = _take_command(buf, at + 8)
        return self.message_type(slot=slot, command=command), at


class ReadRequestCodec(_SlotCommandCodec):
    message_type = ReadRequest
    tag = 121


class SequentialReadRequestCodec(_SlotCommandCodec):
    message_type = SequentialReadRequest
    tag = 122


class EventualReadRequestCodec(MessageCodec):
    message_type = EventualReadRequest
    tag = 123

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return EventualReadRequest(command=command), at


def _put_reply(out: bytearray, reply) -> None:
    """ReadReply and ClientReply share the (command_id, slot, result)
    shape."""
    _put_cid(out, reply.command_id)
    out += _I64.pack(reply.slot)
    _put_bytes(out, reply.result)


def _take_reply(buf: bytes, at: int, cls) -> tuple:
    cid, at = _take_cid(buf, at)
    (slot,) = _I64.unpack_from(buf, at)
    result, at = _take_bytes(buf, at + 8)
    return cls(command_id=cid, slot=slot, result=result), at


class _ReplyBatchCodec(MessageCodec):
    """Shared layout for the (count + replies) batch messages."""

    reply_type: type

    def encode(self, out, message):
        out += _I32.pack(len(message.batch))
        for reply in message.batch:
            _put_reply(out, reply)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        batch = []
        for _ in range(n):
            reply, at = _take_reply(buf, at, self.reply_type)
            batch.append(reply)
        return self.message_type(batch=tuple(batch)), at


class ReadReplyBatchCodec(_ReplyBatchCodec):
    message_type = ReadReplyBatch
    reply_type = ReadReply
    tag = 124


class ClientReplyBatchCodec(_ReplyBatchCodec):
    message_type = ClientReplyBatch
    reply_type = ClientReply
    tag = 125


# The read-BATCHER path and the leader-change client redirects, on the
# extended tag page (133+; primary 1..127 is fully allocated). paxflow
# FLOW405 surfaced the batch shapes: they are named in serve/lanes.py's
# client lane, but the frame-layer classifier is TAG-based, so without
# codecs their pickled frames rode the control lane and could never be
# shed. The redirect shapes (NotLeader*/LeaderInfo*) are hot exactly
# during failover storms, when every queued client op resends at once.


class _CommandsBatchCodec(MessageCodec):
    """Shared layout for the (slot, commands) read request batches."""

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        out += _I32.pack(len(message.commands))
        for command in message.commands:
            _put_command(out, command)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        (n,) = _I32.unpack_from(buf, at + 8)
        at += 12
        commands = []
        for _ in range(n):
            command, at = _take_command(buf, at)
            commands.append(command)
        return self.message_type(slot=slot,
                                 commands=tuple(commands)), at


class ReadRequestBatchCodec(_CommandsBatchCodec):
    message_type = ReadRequestBatch
    tag = 133


class SequentialReadRequestBatchCodec(_CommandsBatchCodec):
    message_type = SequentialReadRequestBatch
    tag = 134


class EventualReadRequestBatchCodec(MessageCodec):
    message_type = EventualReadRequestBatch
    tag = 135

    def encode(self, out, message):
        out += _I32.pack(len(message.commands))
        for command in message.commands:
            _put_command(out, command)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        commands = []
        for _ in range(n):
            command, at = _take_command(buf, at)
            commands.append(command)
        return EventualReadRequestBatch(commands=tuple(commands)), at


class BatchMaxSlotRequestCodec(MessageCodec):
    message_type = BatchMaxSlotRequest
    tag = 136

    def encode(self, out, message):
        out += _QI.pack(message.read_batcher_id,
                        message.read_batcher_index)

    def decode(self, buf, at):
        batcher_id, index = _QI.unpack_from(buf, at)
        return BatchMaxSlotRequest(read_batcher_index=index,
                                   read_batcher_id=batcher_id), at + 12


_QIIIQ = struct.Struct("<qiiiq")


class BatchMaxSlotReplyCodec(MessageCodec):
    message_type = BatchMaxSlotReply
    tag = 137

    def encode(self, out, message):
        out += _QIIIQ.pack(message.read_batcher_id,
                            message.read_batcher_index,
                            message.group_index,
                            message.acceptor_index, message.slot)

    def decode(self, buf, at):
        batcher_id, index, group, acceptor, slot = \
            _QIIIQ.unpack_from(buf, at)
        return BatchMaxSlotReply(read_batcher_index=index,
                                 read_batcher_id=batcher_id,
                                 group_index=group,
                                 acceptor_index=acceptor,
                                 slot=slot), at + _QIIIQ.size


class _EmptyCodec(MessageCodec):
    """Zero-field redirect markers: the tag IS the message."""

    def encode(self, out, message):
        pass

    def decode(self, buf, at):
        return self.message_type(), at


class NotLeaderClientCodec(_EmptyCodec):
    message_type = NotLeaderClient
    tag = 138


class LeaderInfoRequestClientCodec(_EmptyCodec):
    message_type = LeaderInfoRequestClient
    tag = 139


class LeaderInfoReplyClientCodec(MessageCodec):
    message_type = LeaderInfoReplyClient
    tag = 140

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return LeaderInfoReplyClient(round=round), at + 8


class NotLeaderBatcherCodec(MessageCodec):
    message_type = NotLeaderBatcher
    tag = 141

    def encode(self, out, message):
        _put_value(out, message.client_request_batch.batch)

    def decode(self, buf, at):
        batch, at = _take_value(buf, at)
        return NotLeaderBatcher(
            client_request_batch=ClientRequestBatch(batch)), at


class LeaderInfoRequestBatcherCodec(_EmptyCodec):
    message_type = LeaderInfoRequestBatcher
    tag = 142


class LeaderInfoReplyBatcherCodec(MessageCodec):
    message_type = LeaderInfoReplyBatcher
    tag = 143

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return LeaderInfoReplyBatcher(round=round), at + 8


# --- paxwire ack coalescing (tag 152) ---------------------------------------
# A drain's per-message Phase2b stream from one acceptor to one proxy
# leader merges into ONE frame of run-granular ack ranges at the
# TRANSPORT's flush (runtime/paxwire.py coalescer registry): 25 bytes
# per ack become ~32 bytes per contiguous RUN. Receivers expand the
# batch back into the messages the ProxyLeader already handles --
# width-1 entries as plain Phase2b (its never-sent-a-Phase2a tripwire
# stays armed), wider runs as Phase2bRange.

_ACK_RANGE = struct.Struct("<qqqii")  # start, end, round, group, acceptor


@dataclasses.dataclass(frozen=True)
class Phase2bAckBatch:
    """Coalesced Phase2b acks: (start, end, round, group, acceptor)
    runs, in first-ack order."""

    ranges: tuple

    def __wire_expand__(self, serializer):
        for start, end, round, group, acceptor in self.ranges:
            if end - start == 1:
                yield Phase2b(group_index=group, acceptor_index=acceptor,
                              slot=start, round=round)
            else:
                yield Phase2bRange(group_index=group,
                                   acceptor_index=acceptor,
                                   slot_start_inclusive=start,
                                   slot_end_exclusive=end, round=round)


class Phase2bAckBatchCodec(MessageCodec):
    message_type = Phase2bAckBatch
    tag = 152
    # Encoded by the transport's flush-time coalescer, decoded and
    # expanded by the transport -- no role send site (paxflow FLOW403
    # skips transport_layer codecs).
    transport_layer = True

    def encode(self, out, message):
        out += _I32.pack(len(message.ranges))
        for entry in message.ranges:
            out += _ACK_RANGE.pack(*entry)

    def decode(self, buf, at):
        (n,) = _I32.unpack_from(buf, at)
        at += 4
        if n < 0 or at + n * _ACK_RANGE.size > len(buf):
            raise ValueError(
                f"malformed ack batch: count {n} exceeds payload")
        ranges = []
        for _ in range(n):
            ranges.append(_ACK_RANGE.unpack_from(buf, at))
            at += _ACK_RANGE.size
        return Phase2bAckBatch(ranges=tuple(ranges)), at


def _coalesce_phase2b(payloads: list):
    """paxwire coalescer for runs of tag-1 (Phase2b) payloads: merge
    slot-contiguous same-(round, group, acceptor) acks into ranges.
    Acks are commutative on the quorum trackers, so reordering inside
    the run is safe. Returns None (decline -> generic batch frame) on
    any unexpected layout."""
    acks = []
    for payload in payloads:
        if len(payload) != 25 or payload[0] != Phase2bCodec.tag:
            return None
        acks.append(_QQII.unpack_from(payload, 1))
    # Sort by (round, group, acceptor, slot); emit contiguous runs.
    acks.sort(key=lambda a: (a[1], a[2], a[3], a[0]))
    ranges = []
    for slot, round, group, acceptor in acks:
        if ranges:
            start, end, pround, pgroup, pacceptor = ranges[-1]
            if (pround, pgroup, pacceptor) == (round, group, acceptor):
                if slot == end:
                    ranges[-1] = (start, end + 1, pround, pgroup,
                                  pacceptor)
                    continue
                if slot < end:  # duplicate ack; keep it a lone entry
                    ranges.append((slot, slot + 1, round, group,
                                   acceptor))
                    continue
        ranges.append((slot, slot + 1, round, group, acceptor))
    out = bytearray((0, Phase2bAckBatchCodec.tag - 128))
    Phase2bAckBatchCodec().encode(
        out, Phase2bAckBatch(ranges=tuple(ranges)))
    return bytes(out)


def _coalesce_client_replies(payloads: list):
    """paxwire coalescer for runs of tag-118 (ClientReplyArray)
    payloads: one drain can queue several reply arrays to one client
    (one per ChosenRun executed that pass); merge them so the drain's
    replies to that client flush as ONE frame -- and the client's
    reply sink scans ONE column batch (ingest/columns.py
    ReplyColumns). Entries are independent acks, so concatenation in
    send order preserves semantics. Returns None (decline) on any
    unexpected layout."""
    total = 0
    for payload in payloads:
        if len(payload) < 5 or payload[0] != ClientReplyArrayCodec.tag:
            return None
        (n,) = _I32.unpack_from(payload, 1)
        if n < 0:
            return None
        total += n
    out = bytearray((ClientReplyArrayCodec.tag,))
    out += _I32.pack(total)
    for payload in payloads:
        out += payload[5:]
    return bytes(out)


def _register_coalescers() -> None:
    from frankenpaxos_tpu.runtime import paxwire

    paxwire.register_coalescer(Phase2bCodec.tag, _coalesce_phase2b)
    paxwire.register_coalescer(ClientReplyArrayCodec.tag,
                               _coalesce_client_replies)


# --- cold-path codecs (COD301 burn-down, extended tags 153-156) -------------
# The failover path: Phase1a/Phase1b/Nack/Recover are per-leader-change
# rather than per-command, but a failover STORM is exactly when the
# wire is busiest -- and the paxwire batch encoder can only vectorize
# messages with fixed layouts.


class Phase1aCodec(MessageCodec):
    message_type = Phase1a
    tag = 153

    def encode(self, out, message):
        out += _I64I64.pack(message.round, message.chosen_watermark)

    def decode(self, buf, at):
        round, watermark = _I64I64.unpack_from(buf, at)
        return Phase1a(round=round, chosen_watermark=watermark), at + 16


def _put_vote_value(out: bytearray, value) -> None:
    """A Phase1b vote value: the ordinary CommandBatchOrNoop layout
    (kinds 0/1), with a pickled escape hatch (kind 2) for the exotic
    values sim harnesses store in acceptors (the same trade-off as the
    address escape hatch; Phase1b is per-failover, never hot)."""
    if isinstance(value, Noop):
        out.append(0)
        return
    if isinstance(value, CommandBatch):
        tmp = bytearray()
        try:
            _put_value(tmp, value)
        except (AttributeError, TypeError, struct.error):
            pass  # toy commands: fall through to the escape hatch
        else:
            out += tmp
            return
    from frankenpaxos_tpu.runtime import serializer

    out.append(2)
    _put_bytes(out, serializer.guarded_pickle_dumps(
        value, "phase1b vote value"))


def _take_vote_value(buf: bytes, at: int):
    if buf[at] == 2:
        from frankenpaxos_tpu.runtime import serializer

        raw, at = _take_bytes(buf, at + 1)
        return serializer.guarded_pickle_loads(
            bytes(raw), "phase1b vote value"), at
    return _take_value(buf, at)


class Phase1bCodec(MessageCodec):
    """Votes ride (slot, vote_round, value) entries; discovered epochs
    ride as length-prefixed sub-frames through the serializer (the
    reconfig EpochCommit codec, tag 129)."""

    message_type = Phase1b
    tag = 154

    def encode(self, out, message):
        from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER

        out += _I32.pack(message.group_index)
        out += _I32.pack(message.acceptor_index)
        out += _I64.pack(message.round)
        out += _I32.pack(len(message.info))
        for info in message.info:
            out += _I64I64.pack(info.slot, info.vote_round)
            _put_vote_value(out, info.vote_value)
        out += _I32.pack(len(message.epochs))
        for epoch in message.epochs:
            _put_bytes(out, DEFAULT_SERIALIZER.to_bytes(epoch))

    def decode(self, buf, at):
        from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER

        group, acceptor = _I32I32.unpack_from(buf, at)
        (round,) = _I64.unpack_from(buf, at + 8)
        (n,) = _I32.unpack_from(buf, at + 16)
        at += 20
        info = []
        for _ in range(n):
            slot, vote_round = _I64I64.unpack_from(buf, at)
            value, at = _take_vote_value(buf, at + 16)
            info.append(Phase1bSlotInfo(slot=slot, vote_round=vote_round,
                                        vote_value=value))
        (k,) = _I32.unpack_from(buf, at)
        at += 4
        epochs = []
        for _ in range(k):
            raw, at = _take_bytes(buf, at)
            epochs.append(DEFAULT_SERIALIZER.from_bytes(bytes(raw)))
        return Phase1b(group_index=group, acceptor_index=acceptor,
                       round=round, info=tuple(info),
                       epochs=tuple(epochs)), at


class NackCodec(MessageCodec):
    message_type = Nack
    tag = 155

    def encode(self, out, message):
        out += _I64.pack(message.round)

    def decode(self, buf, at):
        (round,) = _I64.unpack_from(buf, at)
        return Nack(round=round), at + 8


class RecoverCodec(MessageCodec):
    message_type = Recover
    tag = 156

    def encode(self, out, message):
        out += _I64.pack(message.slot)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        return Recover(slot=slot), at + 8


for _codec in (Phase2bCodec(), Phase2aCodec(), ChosenCodec(),
               ClientRequestCodec(), ClientRequestBatchCodec(),
               ClientReplyCodec(), ChosenWatermarkCodec(),
               Phase2bRangeCodec(), Phase2bVotesCodec(),
               ClientRequestArrayCodec(), Phase2aRunCodec(),
               ChosenRunCodec(), ClientReplyArrayCodec(),
               MaxSlotRequestCodec(), MaxSlotReplyCodec(),
               ReadRequestCodec(), SequentialReadRequestCodec(),
               EventualReadRequestCodec(), ReadReplyBatchCodec(),
               ClientReplyBatchCodec(), ReadRequestBatchCodec(),
               SequentialReadRequestBatchCodec(),
               EventualReadRequestBatchCodec(),
               BatchMaxSlotRequestCodec(), BatchMaxSlotReplyCodec(),
               NotLeaderClientCodec(), LeaderInfoRequestClientCodec(),
               LeaderInfoReplyClientCodec(), NotLeaderBatcherCodec(),
               LeaderInfoRequestBatcherCodec(),
               LeaderInfoReplyBatcherCodec(), Phase2bAckBatchCodec(),
               Phase1aCodec(), Phase1bCodec(), NackCodec(),
               RecoverCodec()):
    register_codec(_codec)

_register_coalescers()
