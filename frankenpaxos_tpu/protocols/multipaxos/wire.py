"""Fixed-layout binary codecs for the MultiPaxos hot-path messages.

The reference's every message is a protobuf with a per-role oneof
envelope (ProtoSerializer.scala:3-11, multipaxos/MultiPaxos.proto:
489-588). Here the hot-path messages -- the ones a steady-state write
touches: ClientRequest -> Phase2a -> Phase2b -> Chosen -> ClientReply,
plus the gossip/watermark traffic around them -- get hand-laid-out
binary codecs registered with the runtime's HybridSerializer (see
runtime/serializer.py); cold-path messages (Phase1*, reads,
reconfiguration) stay pickled. Layouts are little-endian fixed-width
structs with length-prefixed strings/bytes: decodable from any
language, no code execution on decode, and several times faster than
pickling dataclasses.

Importing this module (protocols.multipaxos does) registers the codecs
process-wide; both sides of every channel share the schema.
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.runtime.serializer import (
    MessageCodec,
    register_codec,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Chosen,
    ChosenWatermark,
    ClientReply,
    ClientRequest,
    ClientRequestBatch,
    Command,
    CommandBatch,
    CommandId,
    Noop,
    NOOP,
    Phase2a,
    Phase2b,
    Phase2bRange,
    Phase2bVotes,
)

_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")
_I32 = struct.Struct("<i")
_QI = struct.Struct("<qi")
_QQII = struct.Struct("<qqii")


def _put_bytes(out: bytearray, data: bytes) -> None:
    out += _I32.pack(len(data))
    out += data


def _take_bytes(buf: bytes, at: int) -> tuple[bytes, int]:
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    return buf[at:at + n], at + n


def _put_address(out: bytearray, address) -> None:
    """Addresses are (host, port) tuples on TCP, plain strings in sims;
    anything else (exotic sim addresses) rides a pickled escape hatch."""
    if (isinstance(address, tuple) and len(address) == 2
            and isinstance(address[0], str)
            and isinstance(address[1], int)):
        host, port = address
        out.append(1)
        _put_bytes(out, host.encode())
        out += _I32.pack(port)
    elif isinstance(address, str):
        out.append(0)
        _put_bytes(out, address.encode())
    else:
        from frankenpaxos_tpu.runtime import serializer

        out.append(2)
        _put_bytes(out, serializer.guarded_pickle_dumps(address, "address"))


def _take_address(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    raw, at = _take_bytes(buf, at)
    if kind == 1:
        (port,) = _I32.unpack_from(buf, at)
        return (raw.decode(), port), at + 4
    if kind == 2:
        from frankenpaxos_tpu.runtime import serializer

        return serializer.guarded_pickle_loads(raw, "address"), at
    return raw.decode(), at


def _put_command(out: bytearray, command: Command) -> None:
    cid = command.command_id
    _put_address(out, cid.client_address)
    out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
    _put_bytes(out, command.command)


def _take_command(buf: bytes, at: int) -> tuple[Command, int]:
    address, at = _take_address(buf, at)
    pseudonym, id = _I64I64.unpack_from(buf, at)
    at += 16
    payload, at = _take_bytes(buf, at)
    return Command(CommandId(address, pseudonym, id), payload), at


def _put_value(out: bytearray, value) -> None:
    """CommandBatchOrNoop."""
    if isinstance(value, Noop):
        out.append(0)
        return
    out.append(1)
    out += _I32.pack(len(value.commands))
    for command in value.commands:
        _put_command(out, command)


def _take_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        return NOOP, at
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    commands = []
    for _ in range(n):
        command, at = _take_command(buf, at)
        commands.append(command)
    return CommandBatch(tuple(commands)), at


class Phase2bCodec(MessageCodec):
    """The single hottest message (2f+1 per slot)."""

    message_type = Phase2b
    tag = 1

    def encode(self, out, message):
        out += _QQII.pack(message.slot, message.round,
                          message.group_index, message.acceptor_index)

    def decode(self, buf, at):
        slot, round, group, acceptor = _QQII.unpack_from(buf, at)
        return Phase2b(group_index=group, acceptor_index=acceptor,
                       slot=slot, round=round), at + 24


class Phase2aCodec(MessageCodec):
    message_type = Phase2a
    tag = 2

    def encode(self, out, message):
        out += _I64I64.pack(message.slot, message.round)
        _put_value(out, message.value)

    def decode(self, buf, at):
        slot, round = _I64I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 16)
        return Phase2a(slot=slot, round=round, value=value), at


class ChosenCodec(MessageCodec):
    message_type = Chosen
    tag = 3

    def encode(self, out, message):
        out += _I64.pack(message.slot)
        _put_value(out, message.value)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        value, at = _take_value(buf, at + 8)
        return Chosen(slot=slot, value=value), at


class ClientRequestCodec(MessageCodec):
    message_type = ClientRequest
    tag = 4

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return ClientRequest(command), at


class ClientRequestBatchCodec(MessageCodec):
    message_type = ClientRequestBatch
    tag = 5

    def encode(self, out, message):
        _put_value(out, message.batch)

    def decode(self, buf, at):
        batch, at = _take_value(buf, at)
        return ClientRequestBatch(batch), at


class ClientReplyCodec(MessageCodec):
    message_type = ClientReply
    tag = 6

    def encode(self, out, message):
        cid = message.command_id
        _put_address(out, cid.client_address)
        out += _I64I64.pack(cid.client_pseudonym, cid.client_id)
        out += _I64.pack(message.slot)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        address, at = _take_address(buf, at)
        pseudonym, id = _I64I64.unpack_from(buf, at)
        (slot,) = _I64.unpack_from(buf, at + 16)
        result, at = _take_bytes(buf, at + 24)
        return ClientReply(CommandId(address, pseudonym, id), slot,
                           result), at


class ChosenWatermarkCodec(MessageCodec):
    message_type = ChosenWatermark
    tag = 7

    def encode(self, out, message):
        out += _I64.pack(message.slot)

    def decode(self, buf, at):
        (slot,) = _I64.unpack_from(buf, at)
        return ChosenWatermark(slot=slot), at + 8


_P2BR = struct.Struct("<qqqii")  # start, end, round, group, acceptor


class Phase2bRangeCodec(MessageCodec):
    message_type = Phase2bRange
    tag = 13

    def encode(self, out, message):
        out += _P2BR.pack(message.slot_start_inclusive,
                          message.slot_end_exclusive, message.round,
                          message.group_index, message.acceptor_index)

    def decode(self, buf, at):
        start, end, round, group, acceptor = _P2BR.unpack_from(buf, at)
        return Phase2bRange(group_index=group, acceptor_index=acceptor,
                            slot_start_inclusive=start,
                            slot_end_exclusive=end,
                            round=round), at + _P2BR.size


class Phase2bVotesCodec(MessageCodec):
    message_type = Phase2bVotes
    # 114: payload records widened from (i32 slot, i32 round) to
    # (i64 slot, i32 round). The tag bump makes any decoder that only
    # knows the 8-byte layout drop the frame loudly (unknown tag)
    # instead of silently mis-decoding 12-byte records.
    tag = 114

    def encode(self, out, message):
        out += _I32.pack(message.group_index)
        out += _I32.pack(message.acceptor_index)
        _put_bytes(out, message.packed)

    def decode(self, buf, at):
        (group,) = _I32.unpack_from(buf, at)
        (acceptor,) = _I32.unpack_from(buf, at + 4)
        packed, at = _take_bytes(buf, at + 8)
        # Validate the packed payload's count against its length HERE,
        # inside decode, so a malformed/hostile payload raises in the
        # transport's corrupt-frame guard (clean log-and-drop) instead
        # of inside the ProxyLeader's handler -- and before
        # unpack_votes2 sizes any allocation by the claimed count.
        from frankenpaxos_tpu import native

        native.check_votes2(packed)
        return Phase2bVotes(group_index=group, acceptor_index=acceptor,
                            packed=packed), at


for _codec in (Phase2bCodec(), Phase2aCodec(), ChosenCodec(),
               ClientRequestCodec(), ClientRequestBatchCodec(),
               ClientReplyCodec(), ChosenWatermarkCodec(),
               Phase2bRangeCodec(), Phase2bVotesCodec()):
    register_codec(_codec)
