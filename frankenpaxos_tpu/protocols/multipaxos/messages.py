"""MultiPaxos wire messages.

Reference behavior: multipaxos/MultiPaxos.proto (one dataclass per
message; the per-role ``<Role>Inbound`` oneof envelopes are unnecessary
in Python -- receive() dispatches on type).
"""

from __future__ import annotations

import dataclasses
from typing import Union

from frankenpaxos_tpu.runtime.transport import Address


@dataclasses.dataclass(frozen=True)
class CommandId:
    """Uniquely identifies a command: (client, pseudonym, id)
    (MultiPaxos.proto CommandId)."""

    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()


@dataclasses.dataclass(frozen=True)
class CommandBatch:
    commands: tuple[Command, ...]


# A log entry value: a batch of commands or a noop filler.
CommandBatchOrNoop = Union[CommandBatch, Noop]


# --- client <-> batcher/leader ---------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientRequestBatch:
    batch: CommandBatch


@dataclasses.dataclass(frozen=True)
class NotLeaderClient:
    pass


@dataclasses.dataclass(frozen=True)
class LeaderInfoRequestClient:
    pass


@dataclasses.dataclass(frozen=True)
class LeaderInfoReplyClient:
    round: int


@dataclasses.dataclass(frozen=True)
class NotLeaderBatcher:
    client_request_batch: ClientRequestBatch


@dataclasses.dataclass(frozen=True)
class LeaderInfoRequestBatcher:
    pass


@dataclasses.dataclass(frozen=True)
class LeaderInfoReplyBatcher:
    round: int


# --- phase 1 ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    chosen_watermark: int


@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    vote_round: int
    vote_value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class Phase1b:
    group_index: int
    acceptor_index: int
    round: int
    info: tuple[Phase1bSlotInfo, ...]
    # Epoch discovery (reconfig/): every EpochCommit this acceptor has
    # WAL-durably accepted, as a tuple of reconfig.messages.EpochCommit.
    # The Flexible-Paxos intersection condition rides here: a new
    # leader's old-epoch read quorum intersects any activated epoch's
    # commit write quorum, so at least one Phase1b reports it and the
    # leader extends Phase1 to cover the new members.
    epochs: tuple = ()


# --- phase 2 ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class Phase2b:
    group_index: int
    acceptor_index: int
    slot: int
    round: int


@dataclasses.dataclass(frozen=True)
class Phase2bRange:
    """One acceptor's votes for a contiguous slot run in one round.

    A TPU-first departure from the reference's per-slot Phase2b
    (MultiPaxos.proto Phase2b): an acceptor that voted a contiguous run
    of Phase2as within one event-loop drain acks them in ONE message,
    making vote traffic (and the ProxyLeader's per-vote Python) scale
    with drains rather than slots -- the shape the vote board's dense
    record_block path consumes directly."""

    group_index: int
    acceptor_index: int
    slot_start_inclusive: int
    slot_end_exclusive: int
    round: int


@dataclasses.dataclass(frozen=True)
class Phase2bVotes:
    """One acceptor's votes for a FRAGMENTED slot set in one drain.

    Thrifty quorum sampling shreds an acceptor's per-drain votes into
    many short runs; rather than one Phase2b(Range) per run, the whole
    drain ships as a single message whose payload is the native vote
    codec's packed array form (native/codec.cpp fpx_pack_votes) -- the
    ProxyLeader unpacks straight into the numpy arrays its quorum
    tracker consumes, so neither side runs per-vote Python."""

    group_index: int
    acceptor_index: int
    packed: bytes  # native.pack_votes2(slots, rounds)


@dataclasses.dataclass(frozen=True)
class ClientRequestArray:
    """A transport-level coalescing of INDEPENDENT client requests.

    Unlike ClientRequestBatch (the reference's batcher output,
    Batcher.scala:60-90, where the whole batch shares ONE log slot and
    so trades latency for throughput), every command here gets its OWN
    slot at the leader -- the array only exists so a client's burst of
    writes crosses the wire as one message per event-loop drain instead
    of one per command. Latency semantics are identical to sending each
    ClientRequest individually; this is the client edge of the
    drain-granular run pipeline (Phase2aRun/ChosenRun)."""

    commands: tuple  # tuple[Command, ...]


@dataclasses.dataclass(frozen=True)
class Phase2aRun:
    """Phase2as for a CONTIGUOUS slot run in one round, one message.

    The proposal-side twin of Phase2bRange: the reference proposes one
    Phase2a per slot (Leader.scala:331-408, one protobuf + one send
    each); a leader that assigned a whole drain's commands contiguous
    slots proposes them in ONE message whose values array lines up with
    [start_slot, start_slot + len(values)). Acceptors store the run as
    one O(1) record and ack it with one Phase2bRange -- per-slot Python
    disappears from the propose/ack path entirely."""

    start_slot: int
    round: int
    values: tuple  # tuple[CommandBatchOrNoop, ...], one per slot


@dataclasses.dataclass(frozen=True)
class ChosenRun:
    """Chosen values for a contiguous slot run, one message per replica
    per drain (vs one Chosen per slot, Replica.scala:572-628)."""

    start_slot: int
    values: tuple  # tuple[CommandBatchOrNoop, ...], one per slot


@dataclasses.dataclass(frozen=True)
class ClientReplyArray:
    """One replica's drain of replies to ONE client, coalesced.

    Entries are (pseudonym, client_id, slot, result) -- the client
    address rides the wire header (the message is addressed to it), so
    per-entry addresses would be dead bytes."""

    entries: tuple  # tuple[(int, int, int, bytes), ...]


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: CommandBatchOrNoop


@dataclasses.dataclass(frozen=True)
class Nack:
    round: int


@dataclasses.dataclass(frozen=True)
class ChosenWatermark:
    slot: int


@dataclasses.dataclass(frozen=True)
class Recover:
    slot: int


# --- replies ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    slot: int
    result: bytes


@dataclasses.dataclass(frozen=True)
class ClientReplyBatch:
    batch: tuple[ClientReply, ...]


# --- reads ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MaxSlotRequest:
    command_id: CommandId


@dataclasses.dataclass(frozen=True)
class MaxSlotReply:
    command_id: CommandId
    group_index: int
    acceptor_index: int
    slot: int


@dataclasses.dataclass(frozen=True)
class ReadRequest:
    slot: int
    command: Command


@dataclasses.dataclass(frozen=True)
class SequentialReadRequest:
    slot: int
    command: Command


@dataclasses.dataclass(frozen=True)
class EventualReadRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ReadReply:
    command_id: CommandId
    slot: int
    result: bytes


@dataclasses.dataclass(frozen=True)
class ReadReplyBatch:
    batch: tuple[ReadReply, ...]


@dataclasses.dataclass(frozen=True)
class ReadRequestBatch:
    slot: int
    commands: tuple[Command, ...]


@dataclasses.dataclass(frozen=True)
class SequentialReadRequestBatch:
    slot: int
    commands: tuple[Command, ...]


@dataclasses.dataclass(frozen=True)
class EventualReadRequestBatch:
    commands: tuple[Command, ...]


# --- read batcher -----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BatchMaxSlotRequest:
    read_batcher_index: int
    read_batcher_id: int


@dataclasses.dataclass(frozen=True)
class BatchMaxSlotReply:
    read_batcher_index: int
    read_batcher_id: int
    group_index: int
    acceptor_index: int
    slot: int
