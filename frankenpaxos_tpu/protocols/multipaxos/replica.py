"""MultiPaxos Replica.

Reference behavior: multipaxos/Replica.scala:151-691. A BufferMap log
(Replica.scala:194), in-order ``execute_log`` advancing the executed
watermark (Replica.scala:394-453), a simple client table (in-order
execution per client), chosen-watermark gossip every N entries with
responsibility round-robin'd across replicas (Replica.scala:421-447), a
randomized hole-recovery timer (Replica.scala:238-260), and deferred
reads parked until their slot executes (Replica.scala:203-211,455-530).
"""

from __future__ import annotations

import dataclasses
import random
import struct
from typing import Optional

from frankenpaxos_tpu.protocols.multipaxos.config import (
    DistributionScheme,
    MultiPaxosConfig,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Chosen,
    ChosenRun,
    ChosenWatermark,
    ClientReply,
    ClientReplyArray,
    ClientReplyBatch,
    Command,
    CommandBatch,
    EventualReadRequest,
    EventualReadRequestBatch,
    Noop,
    ReadReply,
    ReadReplyBatch,
    ReadRequest,
    ReadRequestBatch,
    Recover,
    SequentialReadRequest,
    SequentialReadRequestBatch,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import (
    _put_address,
    _put_bytes,
    _take_address,
    _take_bytes,
    decode_value_array,
    encode_value_array,
)
from frankenpaxos_tpu.runs.records import log_chosen_values, wal_log_chosen_run
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap
from frankenpaxos_tpu.wal import DurableRole, WalChosenRun, WalSnapshot


@dataclasses.dataclass(frozen=True)
class ReplicaOptions:
    log_grow_size: int = 5000
    unsafe_dont_use_client_table: bool = False
    send_chosen_watermark_every_n_entries: int = 100
    recover_log_entry_min_period_s: float = 10.0
    recover_log_entry_max_period_s: float = 20.0
    unsafe_dont_recover: bool = False
    measure_latencies: bool = True
    # paxload read-path admission (serve/admission.py): a replica
    # sheds READ traffic only -- Chosen/ChosenRun deliveries are the
    # write pipeline's control plane and never pass the controller.
    # The in-flight measure here is the deferred-read backlog. All
    # zeros (default) builds no controller.
    admission_token_rate: float = 0.0
    admission_token_burst: float = 0.0
    admission_inflight_limit: int = 0
    admission_inbox_capacity: int = 0
    admission_inbox_policy: str = "reject"
    admission_codel_target_s: float = 0.0
    admission_codel_interval_s: float = 0.1
    admission_retry_after_ms: int = 0


class Replica(Actor, DurableRole):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, state_machine: StateMachine,
                 config: MultiPaxosConfig,
                 options: ReplicaOptions = ReplicaOptions(),
                 collectors: Collectors | None = None, seed: int = 0,
                 wal=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.metrics_latency = collectors.summary(
            "multipaxos_replica_requests_latency_seconds", labels=("type",))
        self.metrics_executed = collectors.counter(
            "multipaxos_replica_executed_commands_total")
        self.metrics_reads = collectors.counter(
            "multipaxos_replica_executed_reads_total")
        self.index = list(config.replica_addresses).index(address)
        self.log: BufferMap = BufferMap(options.log_grow_size)
        self.deferred_reads: BufferMap = BufferMap(options.log_grow_size)
        # Every entry below executed_watermark has been executed; numChosen
        # counts chosen entries -- together they detect pending holes.
        self.executed_watermark = 0
        self.num_chosen = 0
        # (client address, pseudonym) -> (largest executed id, its reply).
        self.client_table: dict[tuple, tuple[int, bytes]] = {}
        # Durability (wal/): chosen entries append to the WAL as they
        # arrive and client replies are held back until on_drain's
        # group-commit fsync releases them (DurableRole), so an
        # acknowledged write is always recoverable from this replica's
        # own log. Compaction snapshots the SM at the executed
        # watermark and reclaims every segment behind it (the
        # watermark GC extended to disk). wal=None is the reference's
        # in-memory behavior.
        self._wal_init(wal)
        # paxload read-path admission (serve/): built only when armed.
        self._deferred_read_count = 0
        self._wm_dirty = False  # executed advanced since last drain
        from frankenpaxos_tpu.serve.admission import (
            AdmissionController,
            options_from_flat,
        )

        admission_options = options_from_flat(options)
        if admission_options is not None:
            self.admission = AdmissionController(
                admission_options, role=f"replica_{self.index}",
                metrics=transport.runtime_metrics)
            transport.note_admission(address, self)
        self.recover_timer = None
        if wal is not None:
            self._recover_from_wal()
        if not options.unsafe_dont_recover:
            self.recover_timer = self.timer(
                "recover",
                self.rng.uniform(options.recover_log_entry_min_period_s,
                                 options.recover_log_entry_max_period_s),
                self._recover)
            if wal is not None and self.executed_watermark < self.num_chosen:
                # Recovered with holes (chosen records above a gap):
                # start hole recovery immediately on rejoin.
                self.recover_timer.start()

    # --- durability -------------------------------------------------------
    def _snapshot_payload(self) -> bytes:
        """SM snapshot + executed watermark + client table, encoded
        with the wire helpers (no code execution on decode except the
        addresses' own escape hatch)."""
        out = bytearray()
        out += struct.pack("<q", self.executed_watermark)
        _put_bytes(out, self.state_machine.to_bytes())
        out += struct.pack("<i", len(self.client_table))
        for (address, pseudonym), (client_id, result) in \
                self.client_table.items():
            _put_address(out, address)
            out += struct.pack("<qq", pseudonym, client_id)
            _put_bytes(out, result)
        return bytes(out)

    def _restore_snapshot(self, payload: bytes) -> None:
        (watermark,) = struct.unpack_from("<q", payload, 0)
        sm_bytes, at = _take_bytes(payload, 8)
        (n,) = struct.unpack_from("<i", payload, at)
        at += 4
        table: dict = {}
        for _ in range(n):
            address, at = _take_address(payload, at)
            pseudonym, client_id = struct.unpack_from("<qq", payload, at)
            result, at = _take_bytes(payload, at + 16)
            table[(address, pseudonym)] = (client_id, result)
        self.state_machine.from_bytes(sm_bytes)
        self.executed_watermark = watermark
        # Every slot below the watermark is chosen and executed; the
        # log is GC'd to the watermark, so replayed/late entries below
        # it read as duplicates (see _log_chosen).
        self.num_chosen = watermark
        self.client_table = table
        self.log.garbage_collect(watermark)
        self.deferred_reads.garbage_collect(watermark)

    def _recover_from_wal(self) -> None:
        for record in self.wal.recover(self.logger):
            if isinstance(record, WalSnapshot):
                # Compaction base: reset, then restore.
                self.log = BufferMap(self.options.log_grow_size)
                self.executed_watermark = 0
                self.num_chosen = 0
                self.client_table = {}
                self._restore_snapshot(record.payload)
            elif isinstance(record, WalChosenRun):
                self._log_chosen(
                    record.start_slot,
                    decode_value_array(record.values))
            else:
                self.logger.fatal(
                    f"unexpected replica WAL record {record!r}")
        # Re-execute the recovered contiguous prefix (deterministic:
        # same entries, same order). Replies are DISCARDED -- every
        # reply the pre-crash replica sent was covered by a synced
        # record, and unacked clients resend (the client table keeps
        # re-execution exactly-once).
        self._execute_log()

    def _log_chosen(self, start_slot: int, values) -> int:
        """Put a contiguous run of chosen values into the log
        (runs/records.py); returns how many were new. Shared by the
        live handlers and WAL replay."""
        new, _ = log_chosen_values(self.log, self.executed_watermark,
                                   start_slot, 1, values)
        self.num_chosen += new
        return new

    def _wal_compact(self) -> None:
        """Snapshot the SM at the executed watermark and reclaim every
        segment behind it -- the in-memory watermark GC extended to
        disk. Chosen-but-unexecuted entries above the watermark (holes
        pending) are re-logged after the snapshot marker."""
        records = []
        for slot, value in self.log.items(start=self.executed_watermark):
            records.append(WalChosenRun(
                start_slot=slot, stride=1,
                values=encode_value_array((value,))))
        self.wal.compact(WalSnapshot(payload=self._snapshot_payload()),
                         records)
        self.log.garbage_collect(self.executed_watermark)
        self.deferred_reads.garbage_collect(self.executed_watermark)

    def on_drain(self) -> None:
        # Drain-granular watermark tail (paxload): the every-N
        # notification above leaves the leader's view up to N-1 slots
        # stale when the pipeline goes quiet mid-decade -- with a
        # watermark-tied in-flight admission budget that staleness is
        # a LIVENESS hole (the span never drops below the limit and
        # every retry is rejected until budgets exhaust). One extra
        # message per drain, from one replica (slot-round-robin),
        # closes the tail.
        if (self._wm_dirty
                and self.executed_watermark
                % self.options.send_chosen_watermark_every_n_entries
                and self.executed_watermark % self.config.num_replicas
                == self.index):
            self._send_chosen_watermark()
        self._wm_dirty = False
        # GROUP COMMIT (DurableRole): one fsync covers every chosen
        # entry this drain logged; only then do the replies it
        # produced go out.
        self._wal_drain()

    def _send_chosen_watermark(self) -> None:
        watermark = ChosenWatermark(slot=self.executed_watermark)
        proxy = self._proxy_replica_address()
        if proxy is not None:
            self._wal_send(proxy, watermark)
        else:
            for leader in self.config.leader_addresses:
                self._wal_send(leader, watermark)

    # --- helpers ----------------------------------------------------------
    def _proxy_replica_address(self) -> Optional[Address]:
        if not self.config.proxy_replica_addresses:
            return None
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_replica_addresses[
                self.rng.randrange(self.config.num_proxy_replicas)]
        return self.config.proxy_replica_addresses[self.index]

    def _recover(self) -> None:
        recover = Recover(slot=self.executed_watermark)
        proxy = self._proxy_replica_address()
        if proxy is not None:
            self.send(proxy, recover)
        else:
            for leader in self.config.leader_addresses:
                self.send(leader, recover)
        self.recover_timer.start()

    def _execute_command(self, slot: int, command: Command,
                         replies: list[ClientReply]) -> None:
        """Execute with exactly-once + reply-once-per-slot-owner semantics
        (Replica.scala:300-344)."""
        cid = command.command_id
        key = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(key)
        if cached is not None:
            largest_id, cached_result = cached
            if cid.client_id < largest_id:
                return
            if cid.client_id == largest_id:
                replies.append(ClientReply(cid, slot, cached_result))
                return
        result = self.state_machine.run(command.command)
        if not self.options.unsafe_dont_use_client_table:
            self.client_table[key] = (cid.client_id, result)
        if slot % self.config.num_replicas == self.index:
            replies.append(ClientReply(cid, slot, result))
        self.metrics_executed.inc()

    def _execute_log(self) -> list[ClientReply]:
        """Execute the contiguous chosen prefix (Replica.scala:394-453)."""
        replies: list[ClientReply] = []
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return replies
            slot = self.executed_watermark
            if isinstance(value, CommandBatch):
                for command in value.commands:
                    self._execute_command(slot, command, replies)
            else:
                assert isinstance(value, Noop)
            reads = self.deferred_reads.get(slot)
            if reads is not None:
                self._process_deferred_reads(reads)
            self.executed_watermark += 1
            self._wm_dirty = True

            every_n = self.options.send_chosen_watermark_every_n_entries
            if (self.executed_watermark % every_n == 0
                    and (self.executed_watermark // every_n)
                    % self.config.num_replicas == self.index):
                self._send_chosen_watermark()

    def _execute_read(self, command: Command) -> ReadReply:
        result = self.state_machine.run(command.command)
        self.metrics_reads.inc()
        return ReadReply(command_id=command.command_id,
                         slot=self.executed_watermark - 1, result=result)

    def _send_read_replies(self, replies: list[ReadReply]) -> None:
        proxy = self._proxy_replica_address()
        if len(replies) > 1 and proxy is not None:
            self.send(proxy, ReadReplyBatch(batch=tuple(replies)))
        else:
            for reply in replies:
                self.send(reply.command_id.client_address, reply)

    def _process_deferred_reads(self, reads: list[Command]) -> None:
        self._deferred_read_count -= len(reads)
        if self.admission is not None:
            self.admission.set_inflight(self._deferred_read_count)
        self._send_read_replies([self._execute_read(c) for c in reads])

    def _admit_read(self, command: Command, sync: bool = True) -> bool:
        """paxload read admission: the in-flight measure is the
        deferred-read backlog; refusal answers the CLIENT (not the
        read batcher) with an explicit Rejected so its backoff engages
        instead of a resend storm. ``sync=False`` skips the backlog
        resync so batch callers can sync ONCE and let ``admit()``'s
        increments accumulate across the batch -- resyncing per
        command would erase them and the limit would never bind
        within one batch."""
        admission = self.admission
        if admission is None:
            return True
        if sync:
            admission.set_inflight(self._deferred_read_count)
        if admission.admit(1):
            return True
        from frankenpaxos_tpu.serve.messages import Rejected

        cid = command.command_id
        self.send(cid.client_address, Rejected(
            entries=((cid.client_pseudonym, cid.client_id),),
            retry_after_ms=admission.retry_after_ms(),
            reason=admission.last_reason))
        return False

    def _defer_read(self, slot: int, command: Command) -> None:
        reads = self.deferred_reads.get(slot)
        if reads is None:
            self.deferred_reads.put(slot, [command])
        else:
            reads.append(command)
        self._deferred_read_count += 1

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        # timed(label) handler latency summaries (Leader.scala:281-293).
        if self.options.measure_latencies:
            with self.metrics_latency.labels(
                    type(message).__name__).time():
                self._receive_impl(src, message)
        else:
            self._receive_impl(src, message)

    def _receive_impl(self, src: Address, message) -> None:
        if isinstance(message, Chosen):
            self._handle_chosen(src, message)
        elif isinstance(message, ChosenRun):
            self._handle_chosen_run(src, message)
        elif isinstance(message, ReadRequest):
            self._handle_read_request(src, message)
        elif isinstance(message, SequentialReadRequest):
            self._handle_sequential_read_request(src, message)
        elif isinstance(message, EventualReadRequest):
            self._handle_eventual_read_request(src, message)
        elif isinstance(message, ReadRequestBatch):
            self._handle_read_request_batch(src, message)
        elif isinstance(message, SequentialReadRequestBatch):
            self._handle_read_request_batch(src, ReadRequestBatch(
                slot=message.slot, commands=message.commands))
        elif isinstance(message, EventualReadRequestBatch):
            self._handle_eventual_read_batch(message)
        else:
            self.logger.fatal(f"unexpected replica message {message!r}")

    def _handle_eventual_read_batch(self, batch) -> None:
        """Batched eventual reads execute immediately (no defer), but
        still pass read admission: each refused command's client gets
        a Rejected, like the single-message path. Sync once per batch
        so the limit binds within it, then settle back to the
        deferred-read backlog."""
        admission = self.admission
        if admission is None:
            commands = batch.commands
        else:
            admission.set_inflight(self._deferred_read_count)
            commands = [c for c in batch.commands
                        if self._admit_read(c, sync=False)]
        try:
            if commands:
                self._send_read_replies(
                    [self._execute_read(c) for c in commands])
        finally:
            if admission is not None:
                admission.set_inflight(self._deferred_read_count)

    def _handle_read_request_batch(self, src: Address,
                                   batch: ReadRequestBatch) -> None:
        """Batched deferrable reads (Replica.scala:478-530
        handleDeferrableReads)."""
        admission = self.admission
        if admission is None:
            # Admission-off fast path: no per-command filter call (the
            # disabled-path budget is one attribute load + is-None per
            # frame, see runtime/actor.py).
            commands = batch.commands
        else:
            admission.set_inflight(self._deferred_read_count)
            commands = [c for c in batch.commands
                        if self._admit_read(c, sync=False)]
        try:
            if not commands:
                return
            if batch.slot >= self.executed_watermark:
                for command in commands:
                    self._defer_read(batch.slot, command)
                return
            self._send_read_replies(
                [self._execute_read(c) for c in commands])
        finally:
            # Settle to the true backlog: deferred reads are in
            # _deferred_read_count; immediately-executed ones release.
            if admission is not None:
                admission.set_inflight(self._deferred_read_count)

    def _wal_log_chosen_run(self, start_slot: int, values,
                            all_new: bool) -> None:
        """Append the run's NEW entries to the WAL (runs/records.py):
        all-new runs log the inbound lazy value array as ONE raw copy;
        a partially-duplicate run falls back to per-new-slot records
        (rare: a resend or post-failover overlap)."""
        wal_log_chosen_run(self.wal, self.log.get, start_slot, 1, values,
                           all_new=all_new, encode=encode_value_array)

    def _handle_chosen(self, src: Address, chosen: Chosen) -> None:
        """(Replica.scala:572-628)."""
        if self._log_chosen(chosen.slot, (chosen.value,)) == 0:
            return  # duplicate Chosen
        if self.wal is not None:
            self._wal_log_chosen_run(chosen.slot, (chosen.value,),
                                     all_new=True)
        replies = self._execute_log()
        if replies:
            proxy = self._proxy_replica_address()
            if proxy is not None:
                self._wal_send(proxy,
                               ClientReplyBatch(batch=tuple(replies)))
            else:
                for reply in replies:
                    self._wal_send(reply.command_id.client_address, reply)
        self._restart_recover_timer()

    def _handle_chosen_run(self, src: Address, run: ChosenRun) -> None:
        """A contiguous drain of chosen values in one message: log the
        whole run, execute once, and ship each client ONE reply array
        for the drain instead of one ClientReply per command."""
        new = self._log_chosen(run.start_slot, run.values)
        if new == 0:
            return
        if self.wal is not None:
            self._wal_log_chosen_run(run.start_slot, run.values,
                                     all_new=(new == len(run.values)))
        replies = self._execute_log()
        if replies:
            proxy = self._proxy_replica_address()
            if proxy is not None:
                self._wal_send(proxy,
                               ClientReplyBatch(batch=tuple(replies)))
            else:
                by_client: dict = {}
                for r in replies:
                    cid = r.command_id
                    by_client.setdefault(cid.client_address, []).append(
                        (cid.client_pseudonym, cid.client_id, r.slot,
                         r.result))
                for address, entries in by_client.items():
                    self._wal_send(address,
                                   ClientReplyArray(entries=tuple(entries)))
        self._restart_recover_timer()

    def _restart_recover_timer(self) -> None:
        # Recover timer runs only while there are unexecuted chosen slots
        # above a hole.
        if self.recover_timer is not None:
            if self.executed_watermark < self.num_chosen:
                self.recover_timer.start()
            else:
                self.recover_timer.stop()

    def _handle_read_request(self, src: Address,
                             request: ReadRequest) -> None:
        """Linearizable read at a slot; defer until executed
        (Replica.scala:455-530)."""
        if not self._admit_read(request.command):
            return
        if request.slot >= self.executed_watermark:
            self._defer_read(request.slot, request.command)
            return
        self.send(src, self._execute_read(request.command))

    def _handle_sequential_read_request(self, src: Address,
                                        request: SequentialReadRequest
                                        ) -> None:
        # Sequential consistency: wait until we've executed past the
        # client's last seen slot (Client.scala:697+).
        self._handle_read_request(src, ReadRequest(slot=request.slot,
                                                   command=request.command))

    def _handle_eventual_read_request(self, src: Address,
                                      request: EventualReadRequest) -> None:
        if not self._admit_read(request.command):
            return
        self.send(src, self._execute_read(request.command))
