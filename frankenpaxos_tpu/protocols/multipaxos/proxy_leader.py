"""MultiPaxos ProxyLeader.

Reference behavior: multipaxos/ProxyLeader.scala:67-259. On Phase2a: fan
the message to a write quorum (thrifty f+1 of the slot's acceptor group,
or a random grid write quorum in flexible mode) and remember the value.
On Phase2b: collect votes per (slot, round) until quorum -- THE hot loop
-- then broadcast Chosen to every replica.

The vote-collection loop is delegated to a
:class:`~frankenpaxos_tpu.protocols.multipaxos.quorum_tracker.QuorumTracker`:
the host-dict oracle or the TPU vote board flushed once per transport
drain (``on_drain``).
"""

from __future__ import annotations

import bisect
import dataclasses
import random

import numpy as np

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Chosen,
    ChosenRun,
    Phase2a,
    Phase2aRun,
    Phase2b,
    Phase2bRange,
    Phase2bVotes,
)
from frankenpaxos_tpu.protocols.multipaxos.quorum_tracker import (
    DictQuorumTracker,
    QuorumTracker,
    TpuQuorumTracker,
)
from frankenpaxos_tpu.reconfig import (
    EpochAck,
    EpochCommit,
    EpochConfig,
    EpochPhase2aRun,
    EpochQuorumTracker,
    EpochStore,
)
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class ProxyLeaderOptions:
    flush_phase2as_every_n: int = 1
    measure_latencies: bool = True
    # "dict" (host oracle) or "tpu" (batched vote board).
    quorum_backend: str = "dict"
    tpu_window: int = 1 << 20
    # Sync-mode host/device routing threshold (drain width in slots);
    # 0 = auto-calibrate to the device platform (see TpuQuorumTracker).
    tpu_min_device_slots: int = 0
    # Pipelined device drains: dispatch this drain's votes async and
    # emit the PREVIOUS drain's results, hiding the device-link RTT
    # behind the event loop (one drain of extra choose latency). A
    # flush timer collects the final dispatch during quiescence.
    tpu_pipelined: bool = False
    tpu_flush_period_s: float = 0.005
    # Reconfiguration (reconfig/): backend for the epoch-segmented
    # tracker once epoch counting engages ("" follows quorum_backend).
    epoch_backend: str = ""
    # Engage the epoch tracker from construction even in a single
    # epoch (the reconfig_lt A/B's tagged arm); otherwise it engages
    # on the first committed epoch change / epoch-tagged run.
    epoch_quorums: bool = False


class ProxyLeader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 options: ProxyLeaderOptions = ProxyLeaderOptions(),
                 collectors: Collectors | None = None, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.metrics_latency = collectors.summary(
            "multipaxos_proxy_leader_requests_latency_seconds", labels=("type",))
        self.metrics_requests = collectors.counter(
            "multipaxos_proxy_leader_requests_total", labels=("type",))
        # Pipelined-mode overlap instrumentation (VERDICT r4 weak #2):
        # how many dispatches are in flight when a new one is queued
        # (depth 0 = no overlap, the link RTT is serialized per drain)
        # and how long each device collect blocks the worker thread.
        self.metrics_tpu_dispatches = collectors.counter(
            "multipaxos_proxy_leader_tpu_dispatches_total")
        self.metrics_tpu_inflight = collectors.summary(
            "multipaxos_proxy_leader_tpu_inflight_at_dispatch")
        self.metrics_tpu_collect = collectors.summary(
            "multipaxos_proxy_leader_tpu_collect_seconds")
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        # paxingest (ingest/): control batch frames of vote acks land
        # as SoA range rows -- no Phase2b/Phase2bRange object per
        # segment (non-ack control batches parse to None and fall back
        # to per-message delivery).
        from frankenpaxos_tpu.ingest.columns import parse_ack_batch
        from frankenpaxos_tpu.runtime.paxwire import CONTROL_BATCH_TAG

        self.wire_sinks = {
            CONTROL_BATCH_TAG: (parse_ack_batch,
                                self._handle_ack_columns),
        }
        # (slot, round) -> pending value; moved to _done once chosen.
        self.pending: dict[tuple[int, int], object] = {}
        self._done: set[tuple[int, int]] = set()
        # Pending Phase2aRuns: start -> [end, round, values, remaining
        # (bool ndarray), left]. One O(1) record per run; chosen slots
        # resolve against it by bisect instead of per-slot dict entries.
        self._runs: dict[int, list] = {}
        self._run_starts: list[int] = []  # sorted (bisect.insort)
        # Completed runs' (start, end, round), kept for the stray-ack
        # fatal check (the per-slot path keeps _done forever; this is
        # the run equivalent, far smaller).
        self._done_runs: list[tuple[int, int, int]] = []
        self.chosen_count = 0
        self._unflushed_phase2as = 0
        if options.quorum_backend == "tpu":
            self.tracker: QuorumTracker = TpuQuorumTracker(
                config, window=options.tpu_window,
                pipelined=options.tpu_pipelined,
                min_device_slots=options.tpu_min_device_slots)
        else:
            self.tracker = DictQuorumTracker(config)
        # Reconfiguration (reconfig/): the epoch store resolves
        # acceptor sets per SLOT once epochs exist; the epoch tracker
        # counts votes by ADDRESS under each slot's epoch spec. Both
        # stay dormant (None tracker, single-epoch store) until a
        # reconfiguration touches this proxy, so the epoch-frozen hot
        # path is byte-identical to the pre-reconfig one.
        self.epochs: "EpochStore | None" = None
        if not config.flexible and config.num_acceptor_groups == 1:
            self.epochs = EpochStore.from_members(
                tuple(config.acceptor_addresses[0]), config.f)
        self._epoch_tracker: "EpochQuorumTracker | None" = None
        # EpochPhase2aRuns for epochs this proxy has not seen the
        # commit for yet: epoch -> [run]; replayed when it arrives.
        self._stashed_epoch_runs: dict[int, list] = {}
        if options.epoch_quorums and self.epochs is not None:
            self._ensure_epoch_tracker()
        self._flush_timer = None
        self._collector = None
        if options.quorum_backend == "tpu" and options.tpu_pipelined:
            # Branch on the transport's CAPABILITY (threaded event loop),
            # not on whether its loop happens to exist yet: a TcpTransport
            # actor constructed before start() must still get the
            # collector thread, and a SimTransport must never (its actors
            # run inline on the caller's thread).
            if transport.threaded:
                # Real transport: fetch device results on ONE daemon
                # worker thread (preserving dispatch order) and post
                # each completion back onto the event loop, so the loop
                # never blocks on the device link. A daemon thread (vs a
                # ThreadPoolExecutor, whose threads are joined at
                # interpreter exit) cannot wedge process shutdown on a
                # dead device link.
                import queue
                import threading

                self._collector = queue.Queue()
                # 1 while the collector thread is inside a device
                # collect (that dispatch has left the queue but is
                # still in flight); single writer, read for metrics.
                self._collecting = 0

                def collect_loop():
                    while True:
                        dispatch = self._collector.get()
                        self._collecting = 1
                        try:
                            self._collect_and_post(dispatch)
                        finally:
                            self._collecting = 0

                threading.Thread(target=collect_loop, daemon=True,
                                 name="tpu-collect").start()
            else:
                # SimTransport: a flush timer collects synchronously
                # (tests fire it explicitly).
                def flush_pending():
                    self._collect_all()
                    if self.tracker.has_pending():
                        self._flush_timer.start()

                self._flush_timer = self.timer(
                    "tpuDrainFlush", options.tpu_flush_period_s,
                    flush_pending)

    def receive(self, src: Address, message) -> None:
        # timed(label) handler latency summaries (Leader.scala:281-293).
        if self.options.measure_latencies:
            with self.metrics_latency.labels(
                    type(message).__name__).time():
                self._receive_impl(src, message)
        else:
            self._receive_impl(src, message)

    def _receive_impl(self, src: Address, message) -> None:
        if isinstance(message, Phase2a):
            self.metrics_requests.labels("Phase2a").inc()
            self._handle_phase2a(src, message)
        elif isinstance(message, Phase2aRun):
            self.metrics_requests.labels("Phase2aRun").inc()
            self._handle_phase2a_run(src, message)
        elif isinstance(message, EpochPhase2aRun):
            self.metrics_requests.labels("EpochPhase2aRun").inc()
            self._handle_epoch_phase2a_run(src, message)
        elif isinstance(message, EpochCommit):
            self.metrics_requests.labels("EpochCommit").inc()
            self._handle_epoch_commit(src, message)
        elif isinstance(message, Phase2b):
            self.metrics_requests.labels("Phase2b").inc()
            self._handle_phase2b(src, message)
        elif isinstance(message, Phase2bRange):
            self.metrics_requests.labels("Phase2bRange").inc()
            self._handle_phase2b_range(src, message)
        elif isinstance(message, Phase2bVotes):
            self.metrics_requests.labels("Phase2bVotes").inc()
            self._handle_phase2b_votes(src, message)
        else:
            self.logger.fatal(f"unexpected proxy leader message {message!r}")

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        key = (phase2a.slot, phase2a.round)
        if key in self.pending:
            self.logger.debug(f"duplicate Phase2a for {key}; ignoring")
            return
        if self.epochs is not None:
            config = self.epochs.epoch_of_slot(phase2a.slot)
            quorum = self.rng.sample(list(config.members),
                                     config.quorum_size)
        elif not self.config.flexible:
            # Multi-group striping is epoch-frozen (no store).
            # paxlint: disable=PAX110
            group = list(self.config.acceptor_addresses[
                phase2a.slot % self.config.num_acceptor_groups])
            quorum = self.rng.sample(group, self.config.f + 1)
        else:
            write_quorum = self.grid.random_write_quorum(self.rng)
            quorum = [
                # paxlint: disable=PAX110 -- grids are epoch-frozen
                self.config.acceptor_addresses[flat // self._row_size]
                [flat % self._row_size] for flat in write_quorum]

        if self.options.flush_phase2as_every_n <= 1:
            for acceptor in quorum:
                self.send(acceptor, phase2a)
        else:
            for acceptor in quorum:
                self.send_no_flush(acceptor, phase2a)
            self._unflushed_phase2as += 1
            if self._unflushed_phase2as >= self.options.flush_phase2as_every_n:
                # Flushing is connection upkeep, not membership: cover
                # every address ever buffered to.
                # paxlint: disable=PAX110
                for group_addresses in self.config.acceptor_addresses:
                    for acceptor in group_addresses:
                        self.flush(acceptor)
                if self.epochs is not None:
                    for acceptor in self.epochs.all_members():
                        self.flush(acceptor)
                self._unflushed_phase2as = 0
        self.pending[key] = phase2a.value

    def _admit_run(self, start_slot: int, round: int, values) -> bool:
        """Install a run's O(1) pending record, evicting a same-start
        LOWER-round predecessor (a new leader re-proposing the window;
        mirroring the acceptor's round-monotone vote store -- keeping
        the old record would swallow the new proposal and strand its
        slots until recovery). False: duplicate (same or stale round)."""
        pending = self._runs.get(start_slot)
        if pending is not None:
            if round <= pending[1]:
                return False
            del self._runs[start_slot]
            i = bisect.bisect_left(self._run_starts, start_slot)
            self._run_starts.pop(i)
            # Remember the evicted (start, end, round) so straggler
            # old-round acks are recognized instead of tripping the
            # stray-ack fatal check.
            bisect.insort(self._done_runs,
                          (start_slot, pending[0], pending[1]))
        self._runs[start_slot] = [
            start_slot + len(values), round, values,
            np.ones(len(values), dtype=bool), len(values)]
        bisect.insort(self._run_starts, start_slot)
        return True

    def _handle_phase2a_run(self, src: Address, run: Phase2aRun) -> None:
        """One write quorum for the whole run (drain-granular thrifty:
        the reference samples per slot, ProxyLeader.scala:67-120; one
        sample per run keeps acceptor-side runs whole), one forwarded
        message per quorum member, one O(1) pending record."""
        if len(run.values) == 0:
            return
        if not self._admit_run(run.start_slot, run.round, run.values):
            return
        if self.epochs is not None:
            # Epoch store = the acceptor-set authority (PAX110): for a
            # plain run the set is the start slot's epoch's (a run
            # never spans epochs -- the leader splits at boundaries).
            config = self.epochs.epoch_of_slot(run.start_slot)
            quorum = self.rng.sample(list(config.members),
                                     config.quorum_size)
        elif not self.config.flexible:
            # Multi-group striping is epoch-frozen (no store); the
            # config read IS the membership authority here.
            # paxlint: disable=PAX110
            group = list(self.config.acceptor_addresses[0])
            quorum = self.rng.sample(group, self.config.f + 1)
        else:
            write_quorum = self.grid.random_write_quorum(self.rng)
            quorum = [
                # paxlint: disable=PAX110 -- grids are epoch-frozen
                self.config.acceptor_addresses[flat // self._row_size]
                [flat % self._row_size] for flat in write_quorum]
        self.broadcast(quorum, run)  # encode the values ONCE

    def _handle_epoch_phase2a_run(self, src: Address,
                                  run: EpochPhase2aRun) -> None:
        """An epoch-tagged run: fan it to ITS epoch's acceptors (as a
        plain Phase2aRun -- acceptors are epoch-agnostic voters) and
        count the acks under that epoch's spec. Unknown epoch: stash
        until the leader's EpochCommit resend lands -- never mis-route
        a new-epoch run to the old set."""
        if self.epochs is None:
            self.logger.fatal(
                "EpochPhase2aRun on a non-reconfigurable config")
        if len(run.values) == 0:
            return
        config = self.epochs.config(run.epoch)
        if config is None:
            self._stashed_epoch_runs.setdefault(run.epoch,
                                                []).append(run)
            return
        self._ensure_epoch_tracker()
        if not self._admit_run(run.start_slot, run.round, run.values):
            return
        quorum = self.rng.sample(list(config.members),
                                 config.quorum_size)
        self.broadcast(quorum, Phase2aRun(
            start_slot=run.start_slot, round=run.round,
            values=run.values))

    def _handle_epoch_commit(self, src: Address,
                             commit: EpochCommit) -> None:
        """Adopt the epoch map entry, switch vote counting onto the
        epoch-segmented tracker, ack the committing leader, and replay
        any runs stashed for this epoch."""
        if self.epochs is None:
            return
        try:
            outcome = self.epochs.offer(
                EpochConfig(epoch=commit.epoch,
                            start_slot=commit.start_slot,
                            f=commit.f, members=commit.members),
                commit.round)
        except ValueError as e:
            self.logger.warn(f"EpochCommit rejected: {e}")
            return
        if outcome == "stale":
            return  # lower-round or non-contiguous: no ack
        self._ensure_epoch_tracker()
        self._epoch_tracker.note_epochs()
        self.send(src, EpochAck(epoch=commit.epoch, round=commit.round))
        for run in self._stashed_epoch_runs.pop(commit.epoch, []):
            self._handle_epoch_phase2a_run(src, run)

    def _ensure_epoch_tracker(self) -> None:
        """Engage epoch-segmented vote counting. Pre-switch state in a
        dict tracker migrates (its (group, index) votes map to
        addresses through the epoch-0 config); the TPU tracker's
        board/spill state cannot be extracted -- quorums straddling
        that switch complete through protocol-level resends (warned)."""
        if self._epoch_tracker is not None or self.epochs is None:
            return
        backend = self.options.epoch_backend or (
            "tpu" if self.options.quorum_backend == "tpu" else "dict")
        self._epoch_tracker = EpochQuorumTracker(
            self.epochs, backend=backend,
            window=min(self.options.tpu_window, 1 << 14))
        if isinstance(self.tracker, DictQuorumTracker):
            for (slot, rnd), votes in self.tracker.states.items():
                if not votes:
                    continue  # Done: the chosen report already left
                for g, i in votes:
                    # One-shot migration of pre-epoch vote state; the
                    # epoch-0 members ARE the config group.
                    # paxlint: disable=PAX110
                    addr = self.config.acceptor_addresses[g][i]
                    self._epoch_tracker.record(slot, rnd, addr)
            self.tracker.states = {}
        elif self.options.quorum_backend == "tpu" \
                and not self.options.epoch_quorums:
            self.logger.warn(
                "tpu quorum tracker state not migrated to the epoch "
                "tracker; in-flight quorums complete via resends")

    def _run_for(self, slot: int, round: int):
        """The pending run covering (slot, round), else None."""
        i = bisect.bisect_right(self._run_starts, slot) - 1
        if i < 0:
            return None
        run = self._runs.get(self._run_starts[i])
        if run is not None and slot < run[0] and run[1] == round:
            return run
        return None

    def _in_done_runs(self, slot: int, round: int) -> bool:
        i = bisect.bisect_right(self._done_runs, (slot, float("inf"),
                                                  float("inf"))) - 1
        if i < 0:
            return False
        # Same-start records can coexist (a retired run plus an evicted
        # lower-round predecessor); check every record sharing the
        # covering start (distinct starts never overlap).
        anchor = self._done_runs[i][0]
        while i >= 0 and self._done_runs[i][0] == anchor:
            _, end, rnd = self._done_runs[i]
            if slot < end and rnd == round:
                return True
            i -= 1
        return False

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        key = (phase2b.slot, phase2b.round)
        if key not in self.pending and self._run_for(*key) is None:
            # Either never proposed here (a fatal bug in the reference,
            # ProxyLeader.scala:227-232) or already chosen. The tracker
            # dedups chosen slots; unknown (slot, round)s are fatal.
            if key not in self._done and not self._in_done_runs(*key):
                self.logger.fatal(
                    f"ProxyLeader got Phase2b for {key} but never sent a "
                    f"Phase2a there")
            return
        if self._epoch_tracker is not None:
            # Epoch mode counts by voter ADDRESS: carried (group,
            # index) coordinates collide across epochs when a
            # replacement reuses a dead member's config slot.
            self._epoch_tracker.record(phase2b.slot, phase2b.round, src)
            return
        self.tracker.record(phase2b.slot, phase2b.round,
                            phase2b.group_index, phase2b.acceptor_index)

    def _handle_phase2b_range(self, src: Address,
                              r: Phase2bRange) -> None:
        """A contiguous run of votes in one message: O(1) Python on the
        device tracker (the dict oracle expands per slot). No per-slot
        pending check here -- every slot in the range was a Phase2a THIS
        proxy leader sent to that acceptor, so each is in ``pending`` or
        already ``_done``; ``_emit_chosen`` dedups either way."""
        if self._epoch_tracker is not None:
            self._epoch_tracker.record_range(
                r.slot_start_inclusive, r.slot_end_exclusive, r.round,
                src)
            return
        self.tracker.record_range(r.slot_start_inclusive,
                                  r.slot_end_exclusive, r.round,
                                  r.group_index, r.acceptor_index)

    def _handle_ack_columns(self, src: Address, acks) -> None:
        """Wire-sink handler (paxingest): a whole batch frame of vote
        acks as (start, end, round, group, acceptor) rows, fed to the
        quorum tracker range-at-a-time. Width-1 rows keep the
        never-sent-a-Phase2a tripwire exactly like _handle_phase2b;
        wider rows follow _handle_phase2b_range's
        no-per-slot-pending-check rationale."""
        self.metrics_requests.labels("AckColumns").inc()
        epoch_tracker = self._epoch_tracker
        for start, end, rnd, group, acceptor in acks.rows.tolist():
            if end - start == 1:
                key = (start, rnd)
                if key not in self.pending \
                        and self._run_for(start, rnd) is None:
                    if key not in self._done \
                            and not self._in_done_runs(start, rnd):
                        self.logger.fatal(
                            f"ProxyLeader got Phase2b for {key} but "
                            f"never sent a Phase2a there")
                    continue
            if epoch_tracker is not None:
                epoch_tracker.record_range(start, end, rnd, src)
            else:
                self.tracker.record_range(start, end, rnd, group,
                                          acceptor)

    def _handle_phase2b_votes(self, src: Address, m) -> None:
        """A packed fragmented-drain ack (Phase2bVotes): unpack with
        the native codec straight into the tracker's arrays -- no
        per-vote Python on either side (same no-pending-check rationale
        as ranges)."""
        from frankenpaxos_tpu import native

        slots, rounds = native.unpack_votes2(m.packed)
        if self._epoch_tracker is not None:
            self._epoch_tracker.record_votes(slots, rounds, src)
            return
        self.tracker.record_votes(slots, rounds, m.group_index,
                                  m.acceptor_index)

    def on_drain(self) -> None:
        # paxtrace drain stage: the batched quorum check (dict tracker
        # or TPU kernel dispatch) plus the Chosen emission it unlocks.
        with self.trace_stage("quorum-kernel"):
            self._emit_chosen(self.tracker.drain())
            if self._epoch_tracker is not None:
                self._emit_chosen(self._epoch_tracker.drain())
        if self._collector is not None:
            while True:
                dispatch = self.tracker.take_dispatch()
                if dispatch is None:
                    break
                self.metrics_tpu_dispatches.inc()
                # Depth includes the dispatch the collector thread is
                # currently blocked on (it left the queue but is in
                # flight): a healthy one-deep pipeline must read 1,
                # not 0 -- 0 means the link RTT is serialized.
                self.metrics_tpu_inflight.observe(
                    self._collector.qsize()
                    + getattr(self, "_collecting", 0))
                self._collector.put(dispatch)
        elif self._flush_timer is not None:
            # (Re)arm the quiescence flush while a dispatch is in
            # flight; the timer collects it if no further messages come.
            self._flush_timer.stop()
            if self.tracker.has_pending():
                self._flush_timer.start()

    def _collect_and_post(self, dispatch) -> None:
        """Runs on the collector thread: block on the device fetch, then
        hand the results back to the single-threaded event loop."""
        try:
            with self.metrics_tpu_collect.time():
                results = self.tracker.collect(dispatch)
            if results:
                self.transport.loop.call_soon_threadsafe(
                    self._emit_chosen, results)
        except RuntimeError as e:
            # Loop closed during teardown: dropping in-flight results is
            # expected, but say so.
            self.logger.debug(f"tpu collect post skipped: {e!r}")
        except Exception as e:  # noqa: BLE001 - surface, don't swallow
            # A swallowed collector error would silently drop this
            # dispatch's Chosen broadcasts and wedge its clients.
            self.logger.error(f"tpu collect failed: {e!r}")

    def _collect_all(self) -> None:
        while True:
            dispatch = self.tracker.take_dispatch()
            if dispatch is None:
                return
            self._emit_chosen(self.tracker.collect(dispatch))

    def _emit_chosen(self, keys) -> None:
        if self._runs and len(keys) > 1:
            self._emit_chosen_grouped(keys)
            return
        for key in keys:
            self._emit_one(key)

    def _emit_one(self, key) -> None:
        value = self.pending.pop(key, None)
        if value is None:
            run = self._run_for(*key)
            if run is not None:
                self._emit_run_segment(run, key[0], key[0] + 1)
            return
        self._done.add(key)
        self.chosen_count += 1
        self.broadcast(self.config.replica_addresses,
                       Chosen(slot=key[0], value=value))

    def _emit_chosen_grouped(self, keys) -> None:
        """Group a drain's chosen (slot, round)s into contiguous
        same-round segments (preserving the tracker's arrival-order
        reporting -- no sort) and emit each run-covered segment as ONE
        ChosenRun per replica; anything outside a run falls back to the
        per-slot path."""
        slots = np.fromiter((k[0] for k in keys), dtype=np.int64,
                            count=len(keys))
        rounds = np.fromiter((k[1] for k in keys), dtype=np.int64,
                             count=len(keys))
        breaks = np.flatnonzero((np.diff(slots) != 1)
                                | (np.diff(rounds) != 0)) + 1
        at = 0
        for b in list(breaks.tolist()) + [len(keys)]:
            if b == at:
                continue
            lo = int(slots[at])
            hi = int(slots[b - 1]) + 1
            rnd = int(rounds[at])
            run = self._run_for(lo, rnd)
            if run is not None and hi <= run[0]:
                self._emit_run_segment(run, lo, hi)
            else:
                for i in range(at, b):
                    self._emit_one((int(slots[i]), rnd))
            at = b

    def _emit_run_segment(self, run: list, lo: int, hi: int) -> None:
        """Emit chosen slots [lo, hi) of one pending run: slice the
        values, one ChosenRun per replica, O(1) bookkeeping."""
        end, rnd, values, remaining, left = run
        start = end - len(values)
        seg = remaining[lo - start:hi - start]
        if not seg.all():
            # A re-report within the segment (cannot happen through the
            # tracker's exactly-once contract, but a different tracker
            # implementation might): emit only the fresh sub-slots.
            for off in np.flatnonzero(seg).tolist():
                self._emit_run_segment(run, lo + off, lo + off + 1)
            return
        seg[:] = False
        n = hi - lo
        run[4] = left - n
        self.chosen_count += n
        # Full-run emission (the steady state: the whole run's quorum
        # completes in one drain) forwards the values object itself --
        # for a LazyValueArray that re-encodes as a raw bytes copy,
        # with no Command ever materialized on this actor.
        seg_values = (values if lo == start and hi == end
                      else values[lo - start:hi - start])
        self.broadcast(self.config.replica_addresses,
                       ChosenRun(start_slot=lo, values=seg_values))
        if run[4] == 0:
            self._retire_run(start)

    def _retire_run(self, start: int) -> None:
        """Fully-chosen run: drop its values, remember (start, end,
        round) for the stray-ack check, prune the starts index."""
        run = self._runs.pop(start)
        bisect.insort(self._done_runs, (start, run[0], run[1]))
        i = bisect.bisect_left(self._run_starts, start)
        if i < len(self._run_starts) and self._run_starts[i] == start:
            self._run_starts.pop(i)
