"""MultiPaxos Client.

Reference behavior: multipaxos/Client.scala:120-1060. Per-pseudonym
pending-operation state machines with resend timers:

  * writes (writeImpl, Client.scala:563-603): ClientRequest to a random
    batcher (or the round's leader when there are no batchers); NotLeader
    bounces trigger LeaderInfoRequest round discovery.
  * linearizable reads (readImpl + handleMaxSlotReply,
    Client.scala:604-700, 851-933): MaxSlotRequest to f+1 of a random
    acceptor group (or a grid read quorum); on quorum, read at
    ``max_slot + num_groups - 1`` (grid: ``max_slot``) at a random
    replica, deferred there until executed.
  * sequential reads (Client.scala:697+): read at the largest slot this
    pseudonym has seen.
  * eventual reads (Client.scala:739+): straight to a random replica.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ClientReply,
    ClientReplyArray,
    ClientRequest,
    ClientRequestArray,
    Command,
    CommandId,
    EventualReadRequest,
    LeaderInfoReplyClient,
    LeaderInfoRequestClient,
    MaxSlotReply,
    MaxSlotRequest,
    NotLeaderClient,
    ReadReply,
    ReadRequest,
    SequentialReadRequest,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runs.client import RetryAdmissionMixin, StagedWriteMixin
from frankenpaxos_tpu.runs.routing import (
    make_fan_router,
    pick_array_destination,
    pick_request_destination,
)
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.serve.backoff import Backoff
from frankenpaxos_tpu.serve.messages import Rejected

Callback = Callable[[bytes], None]


@dataclasses.dataclass(frozen=True)
class ClientOptions:
    resend_client_request_period_s: float = 10.0
    resend_max_slot_requests_period_s: float = 10.0
    resend_read_request_period_s: float = 10.0
    # Performance-debugging unsafe modes (Client.scala:42-53).
    unsafe_read_at_first_slot: bool = False
    unsafe_read_at_i: bool = False
    flush_writes_every_n: int = 1
    flush_reads_every_n: int = 1
    measure_latencies: bool = True
    # Coalesce this event-loop pass's writes into ONE ClientRequestArray
    # to the leader (each command still gets its own slot -- see
    # messages.ClientRequestArray). Flushed by on_drain / flush_writes;
    # resends still go per-request. Bypasses batchers: the array is
    # transport-level coalescing, not slot sharing.
    coalesce_writes: bool = False
    # paxload retry discipline (serve/backoff.py, docs/SERVING.md).
    # retry_budget = 0 keeps the pre-paxload behavior: unlimited
    # resends, Rejected treated as an immediate-backoff retry with no
    # cap. With a budget, EVERY retry (Rejected backoff or timeout
    # failover) consumes it, and exhaustion completes the operation
    # with serve.RETRY_EXHAUSTED -- no request ever wedges silently.
    retry_budget: int = 0
    backoff: Backoff = Backoff()


@dataclasses.dataclass
class _PendingWrite:
    id: int
    command: bytes
    callback: Callback
    resend: object
    attempts: int = 0
    backoff_pending: bool = False


@dataclasses.dataclass
class _MaxSlot:
    # No backoff_pending: while the state is _MaxSlot the only
    # outstanding requests are MaxSlotRequests to acceptors, which
    # carry no admission controller and never draw a Rejected (the
    # state becomes _PendingRead in the same handler that sends the
    # rejectable ReadRequest).
    id: int
    command: bytes
    callback: Callback
    replies: dict[tuple[int, int], int]
    resend: object
    attempts: int = 0


@dataclasses.dataclass
class _PendingRead:
    id: int
    command: bytes
    callback: Callback
    resend: object
    attempts: int = 0
    backoff_pending: bool = False
    # The in-flight read request + target replica, kept so a Rejected
    # read can be re-issued after backoff without re-deriving the slot.
    request: object = None
    replica: object = None


class Client(RetryAdmissionMixin, StagedWriteMixin, Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 options: ClientOptions = ClientOptions(), seed: int = 0,
                 collectors: Collectors | None = None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.metrics_replies = collectors.counter(
            "multipaxos_client_replies_received_total")
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        self.round = 0
        self.ids: dict[int, int] = {}               # pseudonym -> next id
        self.states: dict[int, object] = {}         # pseudonym -> pending op
        self.largest_seen_slots: dict[int, int] = {}  # pseudonym -> slot
        # runs/ retry discipline + coalesce_writes staging.
        self._retry_budget = options.retry_budget
        self._retry_backoff = options.backoff
        self._init_staging()
        # paxfan: consistent ring over the ingest-batcher tier -- a
        # session key (this client, pseudonym) pins to one shard; a
        # resend timeout suspects THAT shard (its keys fail over to
        # the clockwise survivors, everyone else stays pinned); a
        # Rejected floors backoff against the shedding shard only.
        self._fan = make_fan_router(
            config,
            revive_after_s=options.resend_client_request_period_s)
        # One reusable resend timer per pseudonym (vs a fresh Timer per
        # write): timer construction was a measurable per-command cost
        # at drain widths in the thousands.
        self._write_timers: dict[int, object] = {}

    # --- public API -------------------------------------------------------
    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callback] = None) -> None:
        self._check_idle(pseudonym)
        callback = callback or (lambda _: None)
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, id), command))
        if self.options.coalesce_writes:
            # Stage for the end-of-pass array flush (runs/client.py:
            # a burst of call_soon'd closed loops, or reissues inside
            # a delivery drain, coalesce into one array).
            self._stage_write(request.command)
        else:
            self._send_client_request(request)
        timer = self._write_resend_timer(pseudonym)
        timer.start()
        self.states[pseudonym] = _PendingWrite(id, command, callback, timer)
        self.ids[pseudonym] = id + 1

    def _write_resend_timer(self, pseudonym: int):
        timer = self._write_timers.get(pseudonym)
        if timer is None:
            def resend():
                # Reads the CURRENT pending write (the timer outlives
                # individual operations). A timeout is the FAILOVER
                # signal (the leader may be gone) -- re-send on the
                # normal discovery path; with a retry budget set, the
                # failover consumes it like any other retry.
                state = self.states.get(pseudonym)
                if isinstance(state, _PendingWrite):
                    if not self._consume_retry(pseudonym, state,
                                               "failover"):
                        return
                    if self._fan is not None:
                        # paxfan: the timeout suspects THIS key's
                        # shard, so the resend below routes past it
                        # while every other key stays pinned.
                        self._fan.suspect_key(self.address, pseudonym)
                    self._send_client_request(ClientRequest(Command(
                        CommandId(self.address, pseudonym, state.id),
                        state.command)))
                    timer.start()

            timer = self.timer(
                f"resendWrite{pseudonym}",
                self.options.resend_client_request_period_s, resend)
            self._write_timers[pseudonym] = timer
        return timer

    def read(self, pseudonym: int, command: bytes,
             callback: Optional[Callback] = None) -> None:
        """Linearizable quorum read."""
        self._check_idle(pseudonym)
        callback = callback or (lambda _: None)
        id = self.ids.get(pseudonym, 0)
        if self.config.num_read_batchers > 0:
            # Let a read batcher amortize the quorum round
            # (Client.scala:665-690).
            read_request = ReadRequest(
                slot=-1,
                command=Command(CommandId(self.address, pseudonym, id),
                                command))
            batcher = self.config.read_batcher_addresses[
                self.rng.randrange(self.config.num_read_batchers)]
            self.send(batcher, read_request)

            def resend_batched():
                state = self.states.get(pseudonym)
                if not isinstance(state, _PendingRead) \
                        or not self._consume_retry(pseudonym, state,
                                                   "failover"):
                    return
                self.send(batcher, read_request)
                timer.start()

            timer = self.timer(
                f"resendRead{pseudonym}",
                self.options.resend_read_request_period_s, resend_batched)
            timer.start()
            self.states[pseudonym] = _PendingRead(id, command, callback,
                                                  timer,
                                                  request=read_request,
                                                  replica=batcher)
            self.ids[pseudonym] = id + 1
            return
        request = MaxSlotRequest(CommandId(self.address, pseudonym, id))
        if not self.config.flexible:
            group_index = self.rng.randrange(self.config.num_acceptor_groups)
            group = list(self.config.acceptor_addresses[group_index])
            quorum = self.rng.sample(group, self.config.f + 1)
            resend_to = group
        else:
            quorum = [self._acceptor_address(flat)
                      for flat in self.grid.random_read_quorum(self.rng)]
            resend_to = [a for g in self.config.acceptor_addresses
                         for a in g]
        for acceptor in quorum:
            self.send(acceptor, request)

        def resend():
            state = self.states.get(pseudonym)
            if not isinstance(state, _MaxSlot) \
                    or not self._consume_retry(pseudonym, state,
                                               "failover"):
                return
            for acceptor in resend_to:
                self.send(acceptor, request)
            timer.start()

        timer = self.timer(f"resendMaxSlot{pseudonym}",
                           self.options.resend_max_slot_requests_period_s,
                           resend)
        timer.start()
        self.states[pseudonym] = _MaxSlot(id, command, callback, {}, timer)
        self.ids[pseudonym] = id + 1

    def sequential_read(self, pseudonym: int, command: bytes,
                        callback: Optional[Callback] = None) -> None:
        self._check_idle(pseudonym)
        callback = callback or (lambda _: None)
        id = self.ids.get(pseudonym, 0)
        slot = self.largest_seen_slots.get(pseudonym, -1)
        request = SequentialReadRequest(
            slot=slot,
            command=Command(CommandId(self.address, pseudonym, id), command))
        replica = self._random_replica()
        self.send(replica, request)
        timer = self._make_read_resend_timer(pseudonym, replica, request)
        self.states[pseudonym] = _PendingRead(id, command, callback, timer,
                                              request=request,
                                              replica=replica)
        self.ids[pseudonym] = id + 1

    def eventual_read(self, pseudonym: int, command: bytes,
                      callback: Optional[Callback] = None) -> None:
        self._check_idle(pseudonym)
        callback = callback or (lambda _: None)
        id = self.ids.get(pseudonym, 0)
        request = EventualReadRequest(
            Command(CommandId(self.address, pseudonym, id), command))
        replica = self._random_replica()
        self.send(replica, request)
        timer = self._make_read_resend_timer(pseudonym, replica, request)
        self.states[pseudonym] = _PendingRead(id, command, callback, timer,
                                              request=request,
                                              replica=replica)
        self.ids[pseudonym] = id + 1

    # --- helpers ----------------------------------------------------------
    def _check_idle(self, pseudonym: int) -> None:
        if pseudonym in self.states:
            raise RuntimeError(
                f"pseudonym {pseudonym} already has a pending operation; a "
                f"client can have one pending operation per pseudonym")

    def _acceptor_address(self, flat: int) -> Address:
        return self.config.acceptor_addresses[flat // self._row_size][
            flat % self._row_size]

    def _random_replica(self) -> Address:
        return self.config.replica_addresses[
            self.rng.randrange(self.config.num_replicas)]

    def _round_leader(self) -> Address:
        return self.config.leader_addresses[
            self.round_system.leader(self.round)]

    def _send_client_request(self, request: ClientRequest) -> None:
        # runs/routing ladder: ingest disseminators absorb the fan-in
        # (ring-pinned per session -- a dead batcher costs a retry
        # plus a failover to its clockwise survivor, not a wedge) >
        # batchers > the round's leader.
        dst = pick_request_destination(
            self.config, self.rng, self._round_leader, fan=self._fan,
            key=(self.address, request.command.command_id.client_pseudonym))
        self.send(dst, request)

    def _flush_staged(self, staged: list) -> None:
        """Ship writes staged by ``coalesce_writes`` as one array (to
        an ingest disseminator when the config deploys them, else
        straight to the round's leader). The array spans many of this
        client's pseudonyms, so it rides the client-scoped ring key
        (pseudonym -1)."""
        dst = pick_array_destination(self.config, self.rng,
                                     self._round_leader, fan=self._fan,
                                     key=(self.address, -1))
        self.send(dst, ClientRequestArray(commands=tuple(staged)))

    def _note_shed_source(self, src: Address, rejected) -> float:
        """Attribute a Rejected to its ingest shard: floor reissue
        backoff against THAT shard only (runs/client.py hook)."""
        if self._fan is None:
            return 0.0
        from frankenpaxos_tpu.ingest.fan import shard_of_address

        shard = shard_of_address(self.config, src)
        if shard < 0:
            return 0.0
        self._fan.note_shed(shard, rejected.retry_after_ms)
        return self._fan.floor_delay_s(shard)

    def _make_read_resend_timer(self, pseudonym: int, replica: Address,
                                request) -> object:
        def resend():
            state = self.states.get(pseudonym)
            if not isinstance(state, _PendingRead) \
                    or not self._consume_retry(pseudonym, state,
                                               "failover"):
                return
            self.send(replica, request)
            timer.start()

        timer = self.timer(f"resendRead{pseudonym}",
                           self.options.resend_read_request_period_s, resend)
        timer.start()
        return timer

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ClientReply):
            self._handle_client_reply(src, message)
        elif isinstance(message, ClientReplyArray):
            self._handle_client_reply_array(src, message)
        elif isinstance(message, MaxSlotReply):
            self._handle_max_slot_reply(src, message)
        elif isinstance(message, ReadReply):
            self._handle_read_reply(src, message)
        elif isinstance(message, NotLeaderClient):
            self._handle_not_leader(src, message)
        elif isinstance(message, LeaderInfoReplyClient):
            self._handle_leader_info(src, message)
        elif isinstance(message, Rejected):
            self._handle_rejected(src, message)
        else:
            self.logger.fatal(f"unexpected client message {message!r}")

    # --- paxload retry discipline (runs/client.py, docs/SERVING.md) -------
    # Rejected handling + backoff/reissue scheduling live in
    # RetryAdmissionMixin; only the operation re-send is ours.
    def _reissue(self, pseudonym: int, state) -> None:
        if isinstance(state, _PendingWrite):
            request = ClientRequest(Command(
                CommandId(self.address, pseudonym, state.id),
                state.command))
            if self.options.coalesce_writes:
                # Re-enter through the STAGED path: a burst of backoff
                # expiries coalesces back into one ClientRequestArray
                # instead of a retry storm of singles (the storm would
                # re-congest the very leader that just shed us).
                self._stage_write(request.command)
            else:
                self._send_client_request(request)
        elif isinstance(state, _PendingRead) and state.request is not None:
            self.send(state.replica, state.request)

    def _handle_client_reply(self, src: Address, reply: ClientReply) -> None:
        pseudonym = reply.command_id.client_pseudonym
        state = self.states.get(pseudonym)
        if not isinstance(state, _PendingWrite) \
                or reply.command_id.client_id != state.id:
            self.logger.debug(f"stale ClientReply {reply}")
            return
        state.resend.stop()
        self.largest_seen_slots[pseudonym] = max(
            self.largest_seen_slots.get(pseudonym, -1), reply.slot)
        del self.states[pseudonym]
        self.metrics_replies.inc()
        state.callback(reply.result)

    def _handle_client_reply_array(self, src: Address,
                                   array: ClientReplyArray) -> None:
        """A replica's whole drain of replies to this client in one
        message; per-entry resolution mirrors _handle_client_reply."""
        for pseudonym, client_id, slot, result in array.entries:
            state = self.states.get(pseudonym)
            if not isinstance(state, _PendingWrite) \
                    or client_id != state.id:
                self.logger.debug(
                    f"stale reply-array entry for pseudonym {pseudonym}")
                continue
            state.resend.stop()
            self.largest_seen_slots[pseudonym] = max(
                self.largest_seen_slots.get(pseudonym, -1), slot)
            del self.states[pseudonym]
            self.metrics_replies.inc()
            state.callback(result)

    def _handle_max_slot_reply(self, src: Address,
                               reply: MaxSlotReply) -> None:
        pseudonym = reply.command_id.client_pseudonym
        state = self.states.get(pseudonym)
        if not isinstance(state, _MaxSlot) \
                or reply.command_id.client_id != state.id:
            self.logger.debug(f"stale MaxSlotReply {reply}")
            return
        state.replies[(reply.group_index, reply.acceptor_index)] = reply.slot
        if not self.config.flexible:
            if len(state.replies) < self.config.f + 1:
                return
        else:
            flat = {g * self._row_size + i for g, i in state.replies}
            if not self.grid.is_superset_of_read_quorum(flat):
                return

        max_slot = max(state.replies.values())
        if self.options.unsafe_read_at_first_slot:
            slot = 0
        elif self.config.flexible or self.options.unsafe_read_at_i:
            slot = max_slot
        else:
            # Slots round-robin over groups; the true global max voted slot
            # can exceed this group's by at most num_groups - 1.
            slot = max_slot + self.config.num_acceptor_groups - 1
        request = ReadRequest(
            slot=slot,
            command=Command(CommandId(self.address, pseudonym, state.id),
                            state.command))
        replica = self._random_replica()
        self.send(replica, request)
        state.resend.stop()
        timer = self._make_read_resend_timer(pseudonym, replica, request)
        self.states[pseudonym] = _PendingRead(state.id, state.command,
                                              state.callback, timer,
                                              attempts=state.attempts,
                                              request=request,
                                              replica=replica)

    def _handle_read_reply(self, src: Address, reply: ReadReply) -> None:
        pseudonym = reply.command_id.client_pseudonym
        state = self.states.get(pseudonym)
        if not isinstance(state, _PendingRead) \
                or reply.command_id.client_id != state.id:
            self.logger.debug(f"stale ReadReply {reply}")
            return
        state.resend.stop()
        self.largest_seen_slots[pseudonym] = max(
            self.largest_seen_slots.get(pseudonym, -1), reply.slot)
        del self.states[pseudonym]
        state.callback(reply.result)

    def _handle_not_leader(self, src: Address, _: NotLeaderClient) -> None:
        for leader in self.config.leader_addresses:
            self.send(leader, LeaderInfoRequestClient())

    def _handle_leader_info(self, src: Address,
                            reply: LeaderInfoReplyClient) -> None:
        if reply.round <= self.round:
            return
        self.round = reply.round
        # Re-send every pending write to the new round's leader
        # (Client.scala handleLeaderInfoReplyClient).
        for pseudonym, state in self.states.items():
            if isinstance(state, _PendingWrite):
                self._send_client_request(ClientRequest(Command(
                    CommandId(self.address, pseudonym, state.id),
                    state.command)))
