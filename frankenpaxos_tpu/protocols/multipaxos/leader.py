"""MultiPaxos Leader.

Reference behavior: multipaxos/Leader.scala:95-723. A state machine over
{Inactive, Phase1, Phase2}:

  * Phase1 (startPhase1, Leader.scala:409-430): send Phase1a with the
    chosen watermark to f+1 acceptors per group (or a grid read quorum);
    collect Phase1b until per-group quorums (or grid read quorum); adopt
    the highest-vote-round value per slot in [chosen_watermark, max_slot]
    -- `safeValue`, Leader.scala:318-330 -- propose them, jump to Phase2,
    replay pending batches.
  * Phase2 (processClientRequestBatch, Leader.scala:331-408): assign the
    next slot, hand the Phase2a to a proxy leader (round-robin in Hash
    mode, own colocated one otherwise).
  * Nacks bump the round and re-run Phase1 (Leader.scala:669-696);
    Recover triggers a leader change so holes get repaired
    (Leader.scala:698-722); the embedded election participant drives
    Inactive <-> active transitions (Leader.scala:192-203).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from frankenpaxos_tpu.election.basic import (
    ElectionOptions,
    ElectionParticipant,
)
from frankenpaxos_tpu.ingest.columns import (
    CLIENT_ARRAY_TAG,
    parse_client_array,
    parse_client_batch,
    reject_value_suffix,
    value_view,
)
from frankenpaxos_tpu.ingest.messages import (
    IngestCredit,
    IngestRun,
    NotLeaderIngest,
)
from frankenpaxos_tpu.protocols.multipaxos.config import (
    DistributionScheme,
    MultiPaxosConfig,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ChosenWatermark,
    ClientRequest,
    ClientRequestArray,
    ClientRequestBatch,
    CommandBatch,
    LeaderInfoReplyBatcher,
    LeaderInfoReplyClient,
    LeaderInfoRequestBatcher,
    LeaderInfoRequestClient,
    Nack,
    NOOP,
    NotLeaderBatcher,
    NotLeaderClient,
    Phase1a,
    Phase1b,
    Phase2a,
    Phase2aRun,
    Recover,
)
from frankenpaxos_tpu.reconfig import (
    EpochAck,
    EpochCommit,
    EpochConfig,
    EpochPhase2aRun,
    EpochStore,
    Reconfigure,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Collectors, FakeCollectors, Logger
from frankenpaxos_tpu.runtime.paxwire import CLIENT_BATCH_TAG
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class LeaderOptions:
    resend_phase1as_period_s: float = 5.0
    flush_phase2as_every_n: int = 1
    # Assign this many CONSECUTIVE slots to one proxy leader before
    # rotating to the next (Hash scheme only). The reference rotates
    # per slot (Leader.scala:331-408); chunked rotation is the
    # TPU-first layout: each proxy leader's slot space stays
    # contiguous, so acceptors' ranged acks stay whole ranges and the
    # device tracker's drain blocks stay dense instead of shredding
    # into stride-N singles. Pure load balancing -- any proxy leader
    # can handle any slot -- so protocol semantics are unchanged.
    proxy_leader_chunk: int = 256
    noop_flush_period_s: float = 0.0  # 0 disables
    election_options: ElectionOptions = ElectionOptions()
    measure_latencies: bool = True
    # "host": the reference's per-slot safeValue scan. "tpu": one batched
    # ops/value.safe_values masked-argmax over the whole recovery window.
    phase1_backend: str = "host"
    # Tag every run proposal with its epoch (EpochPhase2aRun) even while
    # the store holds a single epoch. Off by default -- the single-epoch
    # steady state pays zero reconfig overhead; the reconfig_lt bench
    # turns this on to measure exactly that tagging cost. Once a real
    # reconfiguration commits, tagging engages regardless.
    epoch_tag_runs: bool = False
    resend_epoch_commit_period_s: float = 1.0
    # paxload admission control (serve/admission.py, docs/SERVING.md).
    # All zeros (the default) admits everything and builds NO
    # controller -- the admission-off hot path is one ``is None`` test.
    # The in-flight budget is tied to the run pipeline's watermark:
    # the live span is next_slot - chosen_watermark, refreshed on
    # every proposal and every ChosenWatermark advance.
    admission_token_rate: float = 0.0
    admission_token_burst: float = 0.0
    admission_inflight_limit: int = 0
    admission_inbox_capacity: int = 0
    admission_inbox_policy: str = "reject"
    admission_codel_target_s: float = 0.0
    admission_codel_interval_s: float = 0.1
    admission_retry_after_ms: int = 0

    def admission_options(self):
        from frankenpaxos_tpu.serve.admission import options_from_flat

        return options_from_flat(self)


class _Inactive:
    pass


@dataclasses.dataclass
class _Phase1:
    # group index -> acceptor index -> Phase1b
    phase1bs: list[dict[int, Phase1b]]
    phase1b_acceptors: set[tuple[int, int]]
    pending_batches: list[ClientRequestBatch]
    resend_phase1as: object  # Timer
    # Address-keyed Phase1bs (reconfig): across epochs, (group, index)
    # coordinates can collide -- a replacement reuses a dead member's
    # config slot -- but addresses cannot.
    by_addr: dict = dataclasses.field(default_factory=dict)
    # The Phase1a in flight, for epoch-discovery extension sends.
    phase1a: Optional[Phase1a] = None


@dataclasses.dataclass
class _Phase2:
    noop_flush: Optional[object] = None  # Timer


@dataclasses.dataclass
class _EpochChange:
    """An epoch change in flight (docs/RECONFIG.md state machine):
    PENDING until a write quorum of OLD-epoch acceptors durably acked
    the EpochCommit (proposals buffer -- the handover window), then
    ACTIVE (buffered proposals open the new epoch's slots) while
    resends keep chasing the stragglers' acks."""

    config: EpochConfig
    commit: EpochCommit
    targets: set
    acks: set
    resend: object  # Timer
    pending: list   # buffered CommandBatchOrNoop values
    activated: bool = False
    # True when RE-driving an adopted epoch (post-failover, or a
    # Phase2 leader learning one from a peer broadcast): same gate --
    # an epoch may be proposed into only once f+1 of its PREDECESSOR's
    # acceptors durably hold its commit, because that is what makes
    # every future leader's Phase1 discover it (chaos-found: an
    # adopted-but-undurable epoch let a later leader re-propose its
    # slots under the old quorums -- a second chosen value).
    recommit: bool = False


class Leader(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 options: LeaderOptions = LeaderOptions(),
                 collectors: Collectors | None = None, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.metrics_latency = collectors.summary(
            "multipaxos_leader_requests_latency_seconds", labels=("type",))
        self.metrics_requests = collectors.counter(
            "multipaxos_leader_requests_total", labels=("type",))
        self.index = list(config.leader_addresses).index(address)
        self.grid = config.quorum_grid() if config.flexible else None
        self._row_size = len(config.acceptor_addresses[0])
        # Live reconfiguration (reconfig/): the epoch store is THE
        # authority for acceptor-set reads on this role (paxlint
        # PAX110). Supported for the workhorse shape -- one
        # non-flexible 2f+1 group; grids and slot-striped multi-group
        # configs stay epoch-frozen.
        self.epochs: Optional[EpochStore] = None
        if not config.flexible and config.num_acceptor_groups == 1:
            self.epochs = EpochStore.from_members(
                tuple(config.acceptor_addresses[0]), config.f)
        self._epoch_change: Optional[_EpochChange] = None
        # Post-failover epoch re-broadcast state: {"epoch", "commits",
        # "pending" (proxies yet to ack), "timer"} or None.
        self._epoch_sync: Optional[dict] = None
        self.round_system = ClassicRoundRobin(config.num_leaders)
        # Active leader's round, or the largest known active round.
        self.round = self.round_system.next_classic_round(0, -1)
        self.next_slot = 0
        self.chosen_watermark = 0
        # Commands admitted while in _Phase1 (sitting in
        # pending_batches with no slot yet): the in-flight resyncs
        # must count them, or a long Phase1 admits without bound.
        self._admitted_backlog = 0
        # paxload admission (serve/): built only when an option arms
        # it, so admission-off deployments keep the exact pre-paxload
        # hot path (Actor.admission stays None for the transports too).
        admission_options = options.admission_options()
        if admission_options is not None:
            from frankenpaxos_tpu.serve.admission import (
                AdmissionController,
            )

            self.admission = AdmissionController(
                admission_options, role=f"leader_{self.index}",
                metrics=transport.runtime_metrics)
            transport.note_admission(address, self)
        self._current_proxy_leader = 0
        self._unflushed_phase2as = 0
        self._chunk_sent = 0
        # paxingest (ingest/, docs/TRANSPORT.md): client batch frames
        # and un-batched coalesced arrays land as SoA columns and
        # propose as ONE run -- the wire-to-device fast path for
        # direct client->leader deployments (batcher'd deployments
        # arrive as IngestRun).
        self.wire_sinks = {
            CLIENT_BATCH_TAG: (parse_client_batch,
                               self._handle_client_columns),
            CLIENT_ARRAY_TAG: (parse_client_array,
                               self._handle_client_columns),
        }
        # paxfan descriptor pipelining: per-batcher drained-seq
        # high-water accumulated across one event-loop pass (the leader
        # drains SEVERAL pipelined runs per pass) and flushed as ONE
        # IngestCredit per batcher in on_drain.
        self._ingest_credit_hw: dict = {}

        # Embedded election participant (Leader.scala:192-203).
        self.election = ElectionParticipant(
            config.leader_election_addresses[self.index], transport, logger,
            config.leader_election_addresses, initial_leader_index=0,
            options=options.election_options, seed=seed)
        self.election.register(
            lambda leader_index: self.leader_change(leader_index == self.index))

        self.state: object = (self._start_phase1(self.round,
                                                 self.chosen_watermark)
                              if self.index == 0 else _Inactive())

    # --- helpers ----------------------------------------------------------
    # Flat grid-index arithmetic for the flexible-grid branch, which
    # runs only when self.epochs is None: grid deployments are
    # epoch-frozen (docs/RECONFIG.md "Supported shapes"), so the static
    # config IS the membership.
    def _acceptor_address(self, flat: int) -> Address:  # paxlint: disable=PAX110
        return self.config.acceptor_addresses[flat // self._row_size][
            flat % self._row_size]

    def _proxy_leader_address(self) -> Address:
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_leader_addresses[
                self._current_proxy_leader]
        return self.config.proxy_leader_addresses[self.index]

    def _advance_proxy_leader(self) -> None:
        self._current_proxy_leader = (
            (self._current_proxy_leader + 1) % self.config.num_proxy_leaders)

    @staticmethod
    def _safe_value(phase1bs, slot: int):
        """Highest-vote-round value for ``slot`` else Noop
        (Leader.scala:318-330)."""
        best_round, best_value = -1, None
        for phase1b in phase1bs:
            for info in phase1b.info:
                if info.slot == slot and info.vote_round > best_round:
                    best_round, best_value = info.vote_round, info.vote_value
        return NOOP if best_value is None else best_value

    def _recover_values(self, phase1: "_Phase1", max_slot: int) -> list:
        """Safe values for ``[chosen_watermark, max_slot]``, one per slot.

        The host path replays ``_safe_value`` per slot; the tpu path lifts
        the whole recovery window into one ``[S, N]`` masked argmax
        (ops/value.safe_values) -- votes become (round, value-id) matrices,
        the device returns each slot's highest-round value id, and the
        host maps ids back to values (Leader.scala:504-576's scan as a
        single reduction).
        """
        slots = range(self.chosen_watermark, max_slot + 1)
        # Multi-epoch recovery: every answering acceptor's votes are
        # scanned for every slot. Non-members of a slot's epoch can
        # hold no votes for it (proposals only ever fan to the epoch's
        # members), so the scan is a superset of the epoch's read
        # quorum -- the safe-value rule over exactly the right config.
        # The tpu phase1 backend indexes votes by (group, index)
        # coordinates, which collide across epochs; the host scan is
        # the multi-epoch path.
        if self.epochs is not None and self.epochs.multi_epoch:
            all_phase1bs = list(phase1.by_addr.values())
            return [self._safe_value(all_phase1bs, s) for s in slots]
        # Non-flexible mode partitions slots over acceptor groups
        # (slot % G owns the slot); in FLEXIBLE mode the "groups" are
        # grid ROWS -- every acceptor votes on every slot, so recovery
        # must scan ALL Phase1bs for every slot. Applying the
        # partitioning rule to a grid dropped reported votes whose
        # acceptor sat in the "wrong" row and recovered Noop over a
        # chosen value (found by the 500x250 soak, multipaxos/f1-grid
        # seed 493: replica logs diverged).
        if self.options.phase1_backend != "tpu":
            if self.config.flexible:
                all_phase1bs = [p for group in phase1.phase1bs
                                for p in group.values()]
                return [self._safe_value(all_phase1bs, s) for s in slots]
            return [
                self._safe_value(
                    phase1.phase1bs[s % self.config.num_acceptor_groups]
                    .values(), s)
                for s in slots
            ]

        import numpy as np

        from frankenpaxos_tpu.ops import value as value_ops

        num_slots = max_slot + 1 - self.chosen_watermark
        if num_slots <= 0:
            return []
        num_groups = self.config.num_acceptor_groups
        n_cols = num_groups * self._row_size
        padded = 1
        while padded < num_slots:
            padded *= 2
        vote_rounds = np.full((padded, n_cols), value_ops.NO_VOTE,
                              dtype=np.int32)
        value_ids = np.zeros((padded, n_cols), dtype=np.int32)
        values_by_id: list = []
        id_by_value: dict = {}
        for group_index, group in enumerate(phase1.phase1bs):
            for acceptor_index, phase1b in group.items():
                col = group_index * self._row_size + acceptor_index
                for info in phase1b.info:
                    if not (self.chosen_watermark <= info.slot <= max_slot):
                        continue
                    # Slot-partitioning filter only in non-flexible
                    # mode (see the host path above).
                    if (not self.config.flexible
                            and info.slot % num_groups != group_index):
                        continue
                    vid = id_by_value.get(info.vote_value)
                    if vid is None:
                        vid = len(values_by_id)
                        id_by_value[info.vote_value] = vid
                        values_by_id.append(info.vote_value)
                    row = info.slot - self.chosen_watermark
                    vote_rounds[row, col] = info.vote_round
                    value_ids[row, col] = vid
        has_vote, chosen = value_ops.safe_values(vote_rounds, value_ids)
        has_vote = np.asarray(has_vote)[:num_slots]
        chosen = np.asarray(chosen)[:num_slots]
        return [values_by_id[int(vid)] if hit else NOOP
                for hit, vid in zip(has_vote, chosen)]

    def _account_sent_slots(self, dst: Address, k: int) -> None:
        """Rotate proxy leaders every `chunk` slots (>= the flush batch,
        so a no-flush run never strands bytes on a just-left dst). The
        ONE place the rotation schedule lives -- shared by the per-slot
        and run proposal paths."""
        self._chunk_sent += k
        chunk = max(self.options.proxy_leader_chunk,
                    self.options.flush_phase2as_every_n)
        if self._chunk_sent >= chunk:
            if self._unflushed_phase2as:
                self.flush(dst)
                self._unflushed_phase2as = 0
            self._advance_proxy_leader()
            self._chunk_sent = 0
        elif (self._unflushed_phase2as
              >= self.options.flush_phase2as_every_n):
            self.flush(dst)
            self._unflushed_phase2as = 0

    def _send_phase2a(self, phase2a: Phase2a,
                      force_flush: bool = False) -> None:
        dst = self._proxy_leader_address()
        if self.options.flush_phase2as_every_n <= 1:
            self.send(dst, phase2a)
        else:
            self.send_no_flush(dst, phase2a)
            self._unflushed_phase2as += 1
        self._account_sent_slots(dst, 1)
        if force_flush and self._unflushed_phase2as:
            self.flush(dst)
            self._unflushed_phase2as = 0

    @property
    def _epoch_tagging(self) -> bool:
        """Whether proposals carry epoch tags: always once a real
        reconfiguration committed (the proxy must never mis-route a
        run across the handover), or forced by ``epoch_tag_runs`` for
        the steady-state overhead A/B."""
        return self.epochs is not None and (
            self.epochs.multi_epoch or self.options.epoch_tag_runs)

    def _epoch_buffering(self) -> "Optional[list]":
        """The pending-change buffer while an epoch change awaits its
        activation quorum (the handover window), else None."""
        change = self._epoch_change
        if change is not None and not change.activated:
            return change.pending
        return None

    def _send_epoch_runs(self, values: tuple) -> None:
        """Propose ``values`` at contiguous slots from ``next_slot`` as
        epoch-tagged runs, SPLIT at epoch activation boundaries -- a
        proposal run never spans two acceptor sets (each segment's
        quorum is one epoch's)."""
        k = len(values)
        at = 0
        while at < k:
            slot = self.next_slot + at
            config = self.epochs.epoch_of_slot(slot)
            end = k
            nxt = self.epochs.config(config.epoch + 1)
            if nxt is not None:
                end = min(k, nxt.start_slot - self.next_slot)
            dst = self._proxy_leader_address()
            self.send(dst, EpochPhase2aRun(
                epoch=config.epoch, start_slot=slot, round=self.round,
                values=tuple(values[at:end])))
            self._account_sent_slots(dst, end - at)
            at = end
        self.next_slot += k

    def _process_client_request_batch(self, batch: ClientRequestBatch) -> None:
        if not isinstance(self.state, _Phase2):
            self.logger.fatal(
                f"leader processing a batch outside Phase2: {self.state}")
        pending = self._epoch_buffering()
        if pending is not None:
            pending.append(batch.batch)
            return
        if self._epoch_tagging:
            self._send_epoch_runs((batch.batch,))
            return
        self._send_phase2a(Phase2a(slot=self.next_slot, round=self.round,
                                   value=batch.batch))
        self.next_slot += 1

    # --- phase 1 ----------------------------------------------------------
    def _phase1_epochs(self) -> list:
        """The epochs a Phase1 recovering ``[chosen_watermark, inf)``
        must hold a read quorum in -- Phase1-with-both-configs across a
        handover (the Flexible-Paxos intersection condition)."""
        return self.epochs.epochs_covering(self.chosen_watermark)

    def _start_phase1(self, round: int, chosen_watermark: int) -> _Phase1:
        phase1a = Phase1a(round=round, chosen_watermark=chosen_watermark)
        if self.epochs is not None:
            # Thrifty f+1 sample per covered epoch (a majority is both
            # the read and write quorum); resend widens to every member.
            # dict.fromkeys, not a set: iteration must stay
            # deterministic (sim replay, golden traces) under string
            # hash randomization.
            targets: dict = {}
            for config in self._phase1_epochs():
                targets.update(dict.fromkeys(self.rng.sample(
                    list(config.members), config.quorum_size)))
            for acceptor in targets:
                self.send(acceptor, phase1a)
        elif not self.config.flexible:
            # self.epochs is None on this path: multi-group striping
            # is epoch-frozen (docs/RECONFIG.md "Supported shapes").
            # paxlint: disable=PAX110
            for group in self.config.acceptor_addresses:
                for acceptor in self.rng.sample(list(group),
                                                self.config.f + 1):
                    self.send(acceptor, phase1a)
        else:
            for flat in self.grid.random_read_quorum(self.rng):
                self.send(self._acceptor_address(flat), phase1a)

        def resend():
            if self.epochs is not None:
                targets: dict = {}
                for config in self._phase1_epochs():
                    targets.update(dict.fromkeys(config.members))
                for acceptor in targets:
                    self.send(acceptor, phase1a)
            else:
                for group in self.config.acceptor_addresses:
                    for acceptor in group:
                        self.send(acceptor, phase1a)
            timer.start()

        timer = self.timer("resendPhase1as",
                           self.options.resend_phase1as_period_s, resend)
        timer.start()
        # Fresh Phase1 = fresh (empty) pending backlog.
        self._admitted_backlog = 0
        return _Phase1(
            phase1bs=[{} for _ in range(self.config.num_acceptor_groups)],
            phase1b_acceptors=set(),
            pending_batches=[],
            resend_phase1as=timer,
            phase1a=phase1a)

    def _make_noop_flush_timer(self) -> Optional[object]:
        """In non-flexible mode with multiple groups, periodically propose
        noops so no acceptor group starves (Leader.scala:240-280)."""
        if self.config.flexible or self.options.noop_flush_period_s <= 0:
            return None

        def flush_noop():
            if not isinstance(self.state, _Phase2):
                self.logger.fatal("noop flush outside Phase2")
            # force_flush: an anti-starvation noop must reach its
            # acceptor group NOW, not sit in a no-flush buffer; and
            # rotation is _send_phase2a's job (an extra advance here
            # would split the proxy-leader chunk and strand buffered
            # Phase2as on the just-left dst).
            self._send_phase2a(Phase2a(slot=self.next_slot, round=self.round,
                                       value=NOOP), force_flush=True)
            self.next_slot += 1
            timer.start()

        timer = self.timer("noopFlush", self.options.noop_flush_period_s,
                           flush_noop)
        timer.start()
        return timer

    def _stop_state_timers(self) -> None:
        if isinstance(self.state, _Phase1):
            self.state.resend_phase1as.stop()
        elif isinstance(self.state, _Phase2) and self.state.noop_flush:
            self.state.noop_flush.stop()

    def _abort_epoch_change(self) -> None:
        """Round churn aborts an in-flight change: the commit was
        round-tagged, so its acks are dead; a successor leader adopting
        the (possibly partially acked) entry from Phase1bs supersedes
        or re-drives it. Buffered proposals are dropped -- clients
        resend, and the replica client table keeps that exactly-once."""
        change = self._epoch_change
        if change is None:
            return
        change.resend.stop()
        if change.pending:
            self.logger.debug(
                f"epoch change aborted with {len(change.pending)} "
                f"buffered proposals (clients will resend)")
        self._epoch_change = None

    def leader_change(self, is_new_leader: bool) -> None:
        """Election callback (Leader.scala:432-459)."""
        self._stop_state_timers()
        self._abort_epoch_change()
        self._stop_epoch_sync()
        if not is_new_leader:
            self.state = _Inactive()
            return
        self.round = self.round_system.next_classic_round(self.index,
                                                          self.round)
        self.state = self._start_phase1(self.round, self.chosen_watermark)

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        # timed(label) handler latency summaries (Leader.scala:281-293).
        if self.options.measure_latencies:
            with self.metrics_latency.labels(
                    type(message).__name__).time():
                self._receive_impl(src, message)
        else:
            self._receive_impl(src, message)

    def _receive_impl(self, src: Address, message) -> None:
        handlers = [
            (Phase1b, "Phase1b", self._handle_phase1b),
            (ClientRequest, "ClientRequest", self._handle_client_request),
            (ClientRequestArray, "ClientRequestArray",
             self._handle_client_request_array),
            (ClientRequestBatch, "ClientRequestBatch",
             self._handle_client_request_batch),
            (IngestRun, "IngestRun", self._handle_ingest_run),
            (LeaderInfoRequestClient, "LeaderInfoRequestClient",
             self._handle_leader_info_request_client),
            (LeaderInfoRequestBatcher, "LeaderInfoRequestBatcher",
             self._handle_leader_info_request_batcher),
            (Nack, "Nack", self._handle_nack),
            (ChosenWatermark, "ChosenWatermark",
             self._handle_chosen_watermark),
            (Recover, "Recover", self._handle_recover),
            (Reconfigure, "Reconfigure", self._handle_reconfigure),
            (EpochAck, "EpochAck", self._handle_epoch_ack),
            (EpochCommit, "EpochCommit", self._handle_epoch_commit),
        ]
        for klass, label, handler in handlers:
            if isinstance(message, klass):
                self.metrics_requests.labels(label).inc()
                handler(src, message)
                return
        self.logger.fatal(f"unexpected leader message {message!r}")

    def _adopt_epochs(self, commits) -> bool:
        """Merge epoch entries discovered in a Phase1b into the store
        (highest round per epoch id wins); returns True when coverage
        changed (the caller extends Phase1a to the new members)."""
        changed = False
        for commit in sorted(commits, key=lambda c: (c.epoch, c.round)):
            try:
                outcome = self.epochs.offer(
                    EpochConfig(epoch=commit.epoch,
                                start_slot=commit.start_slot,
                                f=commit.f, members=commit.members),
                    commit.round)
            except ValueError as e:
                self.logger.warn(f"discovered epoch rejected: {e}")
                continue
            changed = changed or outcome in ("new", "replaced")
        return changed

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1):
            self.logger.debug("Phase1b outside Phase1; ignoring")
            return
        phase1 = self.state
        if phase1b.round != self.round:
            self.logger.debug(
                f"Phase1b in round {phase1b.round} != {self.round}; ignoring")
            self.logger.check_lt(phase1b.round, self.round)
            return

        phase1.by_addr[src] = phase1b
        if self.epochs is not None and phase1b.epochs \
                and self._adopt_epochs(phase1b.epochs):
            # Coverage grew mid-Phase1: the newly discovered epochs'
            # members must answer too before recovery may finish.
            members: dict = {}
            for config in self._phase1_epochs():
                members.update(dict.fromkeys(config.members))
            for acceptor in members:
                if acceptor not in phase1.by_addr:
                    self.send(acceptor, phase1.phase1a)
        if self.epochs is not None and self.epochs.multi_epoch:
            # Phase1-with-both-configs: a read quorum in EVERY epoch
            # still covering undecided slots (quorum intersection per
            # epoch is what makes crossing the handover safe).
            answered = set(phase1.by_addr)
            for config in self._phase1_epochs():
                if not config.has_read_quorum(answered):
                    return
        else:
            phase1.phase1bs[phase1b.group_index][phase1b.acceptor_index] \
                = phase1b
            if not self.config.flexible:
                if any(len(group) < self.config.f + 1
                       for group in phase1.phase1bs):
                    return
            else:
                phase1.phase1b_acceptors.add(
                    (phase1b.group_index, phase1b.acceptor_index))
                flat = {g * self._row_size + i
                        for g, i in phase1.phase1b_acceptors}
                if not self.grid.is_superset_of_read_quorum(flat):
                    return

        max_slot = max(
            (info.slot
             for p1b in phase1.by_addr.values()
             for info in p1b.info),
            default=-1)
        values = self._recover_values(phase1, max_slot)
        for slot, value in zip(range(self.chosen_watermark, max_slot + 1),
                               values):
            if self._epoch_tagging:
                # Route recovery proposals by their slot's epoch so the
                # proxy fans each to the right acceptor set.
                config = self.epochs.epoch_of_slot(slot)
                dst = self._proxy_leader_address()
                self.send(dst, EpochPhase2aRun(
                    epoch=config.epoch, start_slot=slot,
                    round=self.round, values=(value,)))
                self._account_sent_slots(dst, 1)
            else:
                self._send_phase2a(Phase2a(slot=slot, round=self.round,
                                           value=value))
        # next_slot must clear the chosen watermark, not just the voted
        # max: Phase1bs report nothing below the watermark (every slot
        # there is already chosen), so with no votes ABOVE it,
        # ``max_slot + 1`` alone would re-propose fresh commands into
        # already-chosen slots -- choosing a second value for a slot
        # (found by the WAL chaos soak's partition + leader-churn
        # schedules). Any CHOSEN slot >= the watermark is covered by
        # quorum intersection: some Phase1b carries its vote, so
        # max_slot clears it.
        self.next_slot = max(max_slot + 1, self.chosen_watermark)

        phase1.resend_phase1as.stop()
        self.state = _Phase2(self._make_noop_flush_timer())
        if self.epochs is not None and self.epochs.multi_epoch:
            newest_epoch = self.epochs.current().epoch
            reporters = {
                addr for addr, p1b in phase1.by_addr.items()
                if any(c.epoch == newest_epoch for c in p1b.epochs)}
            self._ensure_epoch_durability(reporters)
        for batch in phase1.pending_batches:
            self._process_client_request_batch(batch)
        # The backlog just moved into the span (next_slot advanced per
        # batch); resync so it isn't double-counted.
        self._admitted_backlog = 0
        if self.admission is not None:
            self._sync_inflight()

    def _sync_inflight(self) -> None:
        """Resync the controller to the LIVE in-flight measure:
        proposed-minus-chosen span (the run pipeline's own count of
        outstanding work) plus the Phase1 backlog of admitted-but-
        unslotted commands. Called only where the measure actually
        changes (watermark advances, Phase1 exit) -- between resyncs
        ``admit()``'s own increments accumulate, so the budget binds
        even while next_slot is frozen in Phase1."""
        self.admission.set_inflight(
            self.next_slot - self.chosen_watermark
            + self._admitted_backlog)

    def _admit(self, message, n: int) -> bool:
        """paxload admission for ``n`` client commands (serve/): on
        refusal, answer with explicit Rejected wire replies so clients
        back off instead of re-sending into the congestion.
        Control-plane messages never pass through here -- only the
        three client-request shapes do."""
        admission = self.admission
        if admission is None:
            return True
        if admission.admit(n):
            return True
        from frankenpaxos_tpu.serve.admission import reject_replies_for

        for client, reply in reject_replies_for(
                message, admission.retry_after_ms(),
                admission.last_reason):
            self.send(client, reply)
        return False

    def _admit_prefix(self, commands: tuple) -> tuple:
        """Partial admission for a coalesced array: serve the prefix
        the budget allows, explicitly reject the suffix (one Rejected
        -- all commands in an array come from ONE client)."""
        admission = self.admission
        if admission is None:
            return commands
        k = admission.admit_up_to(len(commands))
        if k < len(commands):
            from frankenpaxos_tpu.serve.admission import reject_replies_for

            for address, reply in reject_replies_for(
                    ClientRequestArray(commands=commands[k:]),
                    retry_after_ms=admission.retry_after_ms(),
                    reason=admission.last_reason):
                self.send(address, reply)
        return commands[:k]

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        if isinstance(self.state, _Inactive):
            self.send(src, NotLeaderClient())
        elif not self._admit(request, 1):
            pass
        elif isinstance(self.state, _Phase1):
            self._admitted_backlog += 1
            self.state.pending_batches.append(
                ClientRequestBatch(CommandBatch((request.command,))))
        else:
            self._process_client_request_batch(
                ClientRequestBatch(CommandBatch((request.command,))))

    def _handle_client_request_array(self, src: Address,
                                     array: ClientRequestArray) -> None:
        """A drain's worth of independent requests: assign each its own
        slot from a CONTIGUOUS block and propose the whole block as one
        Phase2aRun (the per-drain shape of Leader.scala:331-408's
        per-slot processClientRequestBatch)."""
        if not array.commands:
            return
        if isinstance(self.state, _Inactive):
            self.send(src, NotLeaderClient())
            return
        commands = self._admit_prefix(array.commands)
        if not commands:
            return
        if len(commands) < len(array.commands):
            array = ClientRequestArray(commands=commands)
        if isinstance(self.state, _Phase1):
            self._admitted_backlog += len(array.commands)
            for command in array.commands:
                self.state.pending_batches.append(
                    ClientRequestBatch(CommandBatch((command,))))
            return
        self._propose_value_run(
            tuple(CommandBatch((c,)) for c in array.commands))

    def _propose_value_run(self, values) -> None:
        """Post-admission Phase2 proposal of one-value-per-slot
        ``values`` -- a tuple, or a LazyValueArray whose raw segment is
        forwarded without a parse (the ingest fast path). The shared
        tail of the array / wire-column / IngestRun paths."""
        if self.config.num_acceptor_groups > 1 and not self.config.flexible:
            # Slots stripe over acceptor groups (slot % G) in this mode,
            # so a contiguous run has no single acceptor audience; fall
            # back to per-slot proposals (iterating decodes a lazy
            # array -- this config is off the zero-object path).
            for value in values:
                self._send_phase2a(Phase2a(slot=self.next_slot,
                                           round=self.round,
                                           value=value))
                self.next_slot += 1
            return
        pending = self._epoch_buffering()
        if pending is not None:
            # Handover window: the epoch change has not reached its
            # activation quorum yet, and these commands' slots belong
            # to the NEW epoch -- hold them so in-flight runs drain in
            # the old epoch while the commit settles.
            pending.extend(values)
            return
        if self._epoch_tagging:
            self._send_epoch_runs(tuple(values))
            return
        run = Phase2aRun(
            start_slot=self.next_slot, round=self.round, values=values)
        k = len(values)
        self.next_slot += k
        dst = self._proxy_leader_address()
        self.send(dst, run)
        # A run counts as k slots toward the proxy-leader chunk
        # rotation (runs never use the no-flush buffer).
        self._account_sent_slots(dst, k)

    # --- paxingest (ingest/, docs/TRANSPORT.md) ---------------------------
    def _note_ingest(self, cmds: int, nbytes: int) -> None:
        metrics = self.transport.runtime_metrics
        if metrics is not None:
            metrics.ingest_batch(cmds, nbytes)

    def on_drain(self) -> None:
        """Flush accumulated pipelining credits: ONE watermark-granular
        IngestCredit per batcher per drain, regardless of how many runs
        this pass consumed. Control-lane (serve/lanes.py), so shedding
        never wedges the batchers' windows."""
        if self._ingest_credit_hw:
            credits, self._ingest_credit_hw = self._ingest_credit_hw, {}
            for src, hw in credits.items():
                self.send(src, IngestCredit(group_index=0,
                                            watermark_seq=hw))

    def _handle_client_columns(self, src: Address, colrun) -> None:
        """Wire-sink handler: a whole ClientFrameBatch as SoA columns.
        The hot branch proposes the frame as ONE Phase2aRun whose value
        bytes are the clients' own wire bytes (LazyValueArray over the
        scanned segment -- re-encoding is a raw copy); inactive /
        Phase1 / refused-suffix conditions keep per-message
        semantics on the cold path."""
        n = len(colrun)
        if n == 0:
            return
        if isinstance(self.state, _Inactive):
            # One bounce per frame: every segment shares the sending
            # connection, and redirect discovery is per-client anyway.
            self.send(src, NotLeaderClient())
            return
        k = n
        admission = self.admission
        if admission is not None:
            k = admission.admit_up_to(n)
            if k < n:
                for address, reply in colrun.reject_entries(
                        k, admission.retry_after_ms(),
                        admission.last_reason):
                    self.send(address, reply)
            if k == 0:
                return
        if isinstance(self.state, _Phase1):
            self._admitted_backlog += k
            for command in colrun.commands(k):  # cold: Phase1 only
                self.state.pending_batches.append(
                    ClientRequestBatch(CommandBatch((command,))))
            return
        values = colrun.lazy_values(k)
        self._note_ingest(k, len(values.raw))
        self._propose_value_run(values)

    def _handle_ingest_run(self, src: Address, run: IngestRun) -> None:
        """A disseminator's pre-batched run descriptor: assign a
        contiguous slot block and forward the pre-encoded values as one
        Phase2aRun -- the leader touches only run metadata (count, raw
        bytes). ``src`` is the batcher, so the inactive bounce returns
        the RUN for re-routing after leader discovery."""
        values = run.values
        n = len(values)
        if n == 0:
            return
        if isinstance(self.state, _Inactive):
            self.send(src, NotLeaderIngest(group_index=0, run=run))
            return
        # Credit the batcher's pipelining window: this run is consumed
        # on every non-bounce path below (proposed, Phase1-buffered, or
        # fully rejected back to clients). Accumulated per batcher,
        # flushed once in on_drain.
        hw = self._ingest_credit_hw.get(src)
        if hw is None or run.seq > hw:
            self._ingest_credit_hw[src] = run.seq
        k = n
        admission = self.admission
        if admission is not None:
            k = admission.admit_up_to(n)
            if k < n:
                reject_value_suffix(self.send, values, k, admission)
                if k == 0:
                    return
                view = value_view(values)
                values = (view.lazy_values(k) if view is not None
                          else tuple(values)[:k])
        if isinstance(self.state, _Phase1):
            self._admitted_backlog += k
            for value in tuple(values)[:k]:  # cold: Phase1 only
                self.state.pending_batches.append(
                    ClientRequestBatch(value))
            return
        self._note_ingest(k, len(getattr(values, "raw", b"")))
        self._propose_value_run(values)

    def _handle_client_request_batch(self, src: Address,
                                     batch: ClientRequestBatch) -> None:
        if isinstance(self.state, _Inactive):
            # Bounce the batch back so the batcher can re-route it
            # (Leader.scala:606-634).
            self.send(src, NotLeaderBatcher(client_request_batch=batch))
        elif not self._admit(batch, len(batch.batch.commands)):
            pass
        elif isinstance(self.state, _Phase1):
            self._admitted_backlog += len(batch.batch.commands)
            self.state.pending_batches.append(batch)
        else:
            self._process_client_request_batch(batch)

    def _handle_leader_info_request_client(self, src: Address, _) -> None:
        if not isinstance(self.state, _Inactive):
            self.send(src, LeaderInfoReplyClient(round=self.round))

    def _handle_leader_info_request_batcher(self, src: Address, _) -> None:
        if not isinstance(self.state, _Inactive):
            self.send(src, LeaderInfoReplyBatcher(round=self.round))

    def _handle_nack(self, src: Address, nack: Nack) -> None:
        if nack.round <= self.round:
            self.logger.debug(f"stale Nack in round {nack.round}; ignoring")
            return
        if isinstance(self.state, _Inactive):
            self.round = nack.round
        else:
            self.round = self.round_system.next_classic_round(self.index,
                                                              nack.round)
            self.leader_change(is_new_leader=True)

    def _handle_chosen_watermark(self, src: Address,
                                 msg: ChosenWatermark) -> None:
        self.chosen_watermark = max(self.chosen_watermark, msg.slot)
        if self.admission is not None:
            # Drain-granular release: the watermark advance IS the
            # signal that in-flight slots completed their quorums.
            self._sync_inflight()

    def _handle_recover(self, src: Address, recover: Recover) -> None:
        # Re-running Phase1 recovers every unchosen slot below some chosen
        # one (Leader.scala:698-722).
        if not isinstance(self.state, _Inactive):
            self.leader_change(is_new_leader=True)

    # --- reconfiguration (reconfig/, docs/RECONFIG.md) --------------------
    def _handle_reconfigure(self, src: Address,
                            msg: Reconfigure) -> None:
        """Start the leader-driven config-change flow: define epoch
        e+1 over ``msg.members`` with activation watermark ``next_slot``
        (in-flight runs below it drain in the old epoch), broadcast the
        round-tagged EpochCommit, and buffer new proposals until a
        write quorum of OLD-epoch acceptors has durably acked it."""
        if self.epochs is None:
            self.logger.warn(
                "Reconfigure ignored: epochs need a single non-flexible "
                "acceptor group")
            return
        if not isinstance(self.state, _Phase2):
            self.logger.debug("Reconfigure ignored outside Phase2 "
                              "(admin should retry at the leader)")
            return
        if self._epoch_change is not None:
            if not self._epoch_change.activated:
                self.logger.debug(
                    "Reconfigure ignored: a change is mid-activation")
                return
            # The previous change is ACTIVE and only chasing straggler
            # acks (possibly of dead members); the new change's commit
            # flow supersedes those resends.
            self._abort_epoch_change()
        current = self.epochs.current()
        members = tuple(msg.members)
        if members == current.members:
            return
        if self.next_slot < current.start_slot:
            # This leader adopted the current epoch but has not
            # proposed up to its activation watermark yet; a successor
            # epoch must start at or above it (epoch starts are
            # monotone). Let the admin retry once caught up.
            self.logger.debug("Reconfigure ignored: next_slot below "
                              "the current epoch's start")
            return
        try:
            config = EpochConfig(epoch=current.epoch + 1,
                                 start_slot=self.next_slot,
                                 f=self.config.f, members=members)
        except ValueError as e:
            self.logger.warn(f"Reconfigure rejected: {e}")
            return
        self._drive_epoch_change(config, predecessor=current,
                                 recommit=False)

    def _drive_epoch_change(self, config: EpochConfig,
                            predecessor: "EpochConfig | None",
                            recommit: bool) -> None:
        """Broadcast + resend one epoch's commit until the activation
        gate (f+1 of the PREDECESSOR's acceptors durably acked) opens;
        proposals buffer meanwhile (the handover window). Targets:
        both acceptor sets (old = the matchmakers, new = the set that
        must know its own era), every proxy leader (they route and
        count -- and their acks release stashed epoch-tagged runs),
        every peer leader (so a failover has the map before its Phase1
        even asks)."""
        commit = EpochCommit(epoch=config.epoch,
                             start_slot=config.start_slot,
                             f=config.f, round=self.round,
                             members=config.members)
        targets: dict = dict.fromkeys(
            predecessor.members if predecessor else ())
        targets.update(dict.fromkeys(config.members))
        targets.update(dict.fromkeys(self.config.proxy_leader_addresses))
        targets.update(dict.fromkeys(
            a for a in self.config.leader_addresses if a != self.address))

        def resend():
            change = self._epoch_change
            if change is None or change.config is not config:
                return
            for dst in change.targets:
                if dst not in change.acks:
                    self.send(dst, change.commit)
            timer.start()

        timer = self.timer("resendEpochCommit",
                           self.options.resend_epoch_commit_period_s,
                           resend)
        timer.start()
        self._epoch_change = _EpochChange(
            config=config, commit=commit, targets=set(targets),
            acks=set(), resend=timer, pending=[], recommit=recommit)
        for dst in targets:
            self.send(dst, commit)

    def _ensure_epoch_durability(self, reporters) -> None:
        """Before this leader proposes into an ADOPTED newest epoch,
        its commit must be provably durable at f+1 of its
        predecessor's acceptors (else a future Phase1 could miss it
        and re-propose its slots under the old quorums). ``reporters``
        are the acceptors whose Phase1bs carried the epoch. Two proofs
        stand: the reporters already form the predecessor write quorum,
        or the chosen watermark is STRICTLY past the epoch's activation
        slot -- a slot chosen UNDER the epoch implies, inductively,
        that some gate-compliant leader activated it with the durable
        quorum (whose WALs outlive any crash). Proven: only the proxies
        need a gateless resync. Unproven: drive a GATED re-commit that
        buffers proposals until the predecessor quorum acks."""
        newest = self.epochs.current()
        pred = self.epochs.config(newest.epoch - 1)
        if pred is None or pred.has_write_quorum(reporters) \
                or self.chosen_watermark > newest.start_slot:
            self._start_epoch_sync()
            return
        self._drive_epoch_change(newest, predecessor=pred,
                                 recommit=True)

    def _start_epoch_sync(self) -> None:
        sync_commits = [
            EpochCommit(epoch=c.epoch, start_slot=c.start_slot, f=c.f,
                        round=self.round, members=c.members)
            for c in self.epochs.known()[1:]]
        pending = set(self.config.proxy_leader_addresses)

        def resend():
            sync = self._epoch_sync
            if sync is None or sync["commits"] is not sync_commits:
                return
            for dst in sync["pending"]:
                for commit in sync_commits:
                    self.send(dst, commit)
            timer.start()

        timer = self.timer("resendEpochSync",
                           self.options.resend_epoch_commit_period_s,
                           resend)
        timer.start()
        self._epoch_sync = {"epoch": sync_commits[-1].epoch,
                            "commits": sync_commits,
                            "pending": pending, "timer": timer}
        for dst in self.config.proxy_leader_addresses:
            for commit in sync_commits:
                self.send(dst, commit)

    def _stop_epoch_sync(self) -> None:
        if self._epoch_sync is not None:
            self._epoch_sync["timer"].stop()
            self._epoch_sync = None

    def _handle_epoch_ack(self, src: Address, ack: EpochAck) -> None:
        sync = self._epoch_sync
        if sync is not None and ack.epoch == sync["epoch"] \
                and ack.round == self.round:
            sync["pending"].discard(src)
            if not sync["pending"]:
                self._stop_epoch_sync()
        change = self._epoch_change
        if change is None or ack.epoch != change.config.epoch \
                or ack.round != self.round:
            return
        change.acks.add(src)
        if not change.activated:
            pred = self.epochs.config(change.config.epoch - 1)
            if pred is None or pred.has_write_quorum(change.acks):
                # COMMIT POINT: f+1 predecessor-epoch acceptors hold
                # the epoch WAL-durably -- any future leader's
                # old-epoch read quorum will discover it. Activate:
                # the buffered proposals open the new epoch's slots.
                try:
                    self.epochs.offer(change.config, self.round)
                except ValueError as e:
                    # The store moved under the change (a concurrent
                    # adoption): abort; clients resend the buffer.
                    self.logger.warn(f"epoch activation aborted: {e}")
                    self._abort_epoch_change()
                    return
                change.activated = True
                # Post-activation the resends only need to reach the
                # parties that ROUTE by the epoch (proxies) and the
                # new members; stop chasing old-epoch/peer-leader
                # stragglers -- in the canonical repair the
                # reconfigured-OUT member is dead and would be pinged
                # forever.
                change.targets &= (
                    set(self.config.proxy_leader_addresses)
                    | set(change.config.members))
                pending, change.pending = change.pending, []
                if pending:
                    self._send_epoch_runs(tuple(pending))
        if change.activated and change.targets <= change.acks:
            change.resend.stop()
            self._epoch_change = None

    def _handle_epoch_commit(self, src: Address,
                             commit: EpochCommit) -> None:
        """A peer leader's commit broadcast: adopt the entry (so this
        leader's next Phase1 covers it without discovery) and ack so
        the committer's resends stop."""
        if self.epochs is None:
            return
        try:
            outcome = self.epochs.offer(
                EpochConfig(epoch=commit.epoch,
                            start_slot=commit.start_slot,
                            f=commit.f, members=commit.members),
                commit.round)
        except ValueError as e:
            self.logger.warn(f"peer EpochCommit rejected: {e}")
            return
        if outcome in ("new", "replaced", "dup"):
            self.send(src, EpochAck(epoch=commit.epoch,
                                    round=commit.round))
        if outcome in ("new", "replaced") \
                and isinstance(self.state, _Phase2) \
                and self._epoch_change is None:
            # An ACTIVE leader adopting a peer's epoch mid-Phase2: it
            # must not propose into the adopted epoch on the peer's
            # word alone -- gate on its own durable predecessor-quorum
            # proof exactly like the post-Phase1 path.
            self._ensure_epoch_durability(reporters=())
