"""MultiPaxos ReadBatcher.

Reference behavior: multipaxos/ReadBatcher.scala:28-640. Batches client
reads to amortize the quorum MaxSlot round:

  * ``size,N,timeout``: flush at N reads, or at the timeout;
  * ``time,timeout``: flush on a period;
  * ``adaptive``: (linearizable only) a new batch starts as soon as the
    previous batch's max-slot quorum resolves -- batch size adapts to
    quorum latency.

Linearizable flushes send one BatchMaxSlotRequest (tagged with a batch
id) to f+1 of a random acceptor group; on an f+1 quorum of replies the
whole batch reads at ``max_slot + num_groups - 1`` at a random replica.
Sequential/eventual batches go straight to a replica.
"""

from __future__ import annotations

import dataclasses
import random

from frankenpaxos_tpu.protocols.multipaxos.config import MultiPaxosConfig
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    BatchMaxSlotReply,
    BatchMaxSlotRequest,
    Command,
    EventualReadRequest,
    EventualReadRequestBatch,
    ReadRequest,
    ReadRequestBatch,
    SequentialReadRequest,
    SequentialReadRequestBatch,
)
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class ReadBatchingScheme:
    """kind in {"size", "time", "adaptive"} (ReadBatcher.scala:28-66)."""

    kind: str = "size"
    batch_size: int = 10
    timeout_s: float = 1.0


class ReadBatcher(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: MultiPaxosConfig,
                 scheme: ReadBatchingScheme = ReadBatchingScheme(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        if scheme.kind not in ("size", "time", "adaptive"):
            raise ValueError(f"unknown read batching scheme {scheme.kind}")
        self.config = config
        self.scheme = scheme
        self.rng = random.Random(seed)
        self.index = list(config.read_batcher_addresses).index(address)
        self._row_size = len(config.acceptor_addresses[0])
        self.grid = config.quorum_grid() if config.flexible else None

        self.linearizable_id = 0
        self.linearizable_batch: list[Command] = []
        self.pending_linearizable: dict[int, list[Command]] = {}
        self.batch_max_slot_replies: dict[int, dict[int, int]] = {}
        # Adaptive: is a max-slot quorum in flight?
        self._adaptive_inflight = False

        self.sequential_slot = -1
        self.sequential_batch: list[Command] = []
        self.eventual_batch: list[Command] = []

        if scheme.kind in ("size", "time"):
            self.linearizable_timer = self.timer(
                "linearizableTimer", scheme.timeout_s,
                self._flush_linearizable_timer)
            self.linearizable_timer.start()
            self.sequential_timer = self.timer(
                "sequentialTimer", scheme.timeout_s,
                self._flush_sequential_timer)
            self.sequential_timer.start()
            self.eventual_timer = self.timer(
                "eventualTimer", scheme.timeout_s,
                self._flush_eventual_timer)
            self.eventual_timer.start()
        else:
            self.linearizable_timer = None
            self.sequential_timer = None
            self.eventual_timer = None

    # --- flushing ---------------------------------------------------------
    def _flush_linearizable(self) -> None:
        if not self.linearizable_batch:
            return
        request = BatchMaxSlotRequest(read_batcher_index=self.index,
                                      read_batcher_id=self.linearizable_id)
        if not self.config.flexible:
            group = list(self.config.acceptor_addresses[
                self.rng.randrange(self.config.num_acceptor_groups)])
            quorum = self.rng.sample(group, self.config.f + 1)
        else:
            quorum = [
                self.config.acceptor_addresses[flat // self._row_size]
                [flat % self._row_size]
                for flat in self.grid.random_read_quorum(self.rng)]
        for acceptor in quorum:
            self.send(acceptor, request)
        self.batch_max_slot_replies[self.linearizable_id] = {}
        self.pending_linearizable[self.linearizable_id] = \
            self.linearizable_batch
        self.linearizable_id += 1
        self.linearizable_batch = []
        self._adaptive_inflight = True

    def _flush_linearizable_timer(self) -> None:
        self._flush_linearizable()
        self.linearizable_timer.start()

    def _flush_sequential(self) -> None:
        if not self.sequential_batch:
            return
        self.send(self._random_replica(), SequentialReadRequestBatch(
            slot=self.sequential_slot,
            commands=tuple(self.sequential_batch)))
        self.sequential_slot = -1
        self.sequential_batch = []

    def _flush_sequential_timer(self) -> None:
        self._flush_sequential()
        self.sequential_timer.start()

    def _flush_eventual(self) -> None:
        if not self.eventual_batch:
            return
        self.send(self._random_replica(), EventualReadRequestBatch(
            commands=tuple(self.eventual_batch)))
        self.eventual_batch = []

    def _flush_eventual_timer(self) -> None:
        self._flush_eventual()
        self.eventual_timer.start()

    def _random_replica(self) -> Address:
        return self.config.replica_addresses[
            self.rng.randrange(self.config.num_replicas)]

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        if isinstance(message, ReadRequest):
            self._handle_read_request(src, message)
        elif isinstance(message, SequentialReadRequest):
            self._handle_sequential(src, message)
        elif isinstance(message, EventualReadRequest):
            self._handle_eventual(src, message)
        elif isinstance(message, BatchMaxSlotReply):
            self._handle_batch_max_slot_reply(src, message)
        else:
            self.logger.fatal(f"unexpected read batcher message {message!r}")

    def _handle_read_request(self, src: Address,
                             request: ReadRequest) -> None:
        self.linearizable_batch.append(request.command)
        if self.scheme.kind == "size":
            if len(self.linearizable_batch) >= self.scheme.batch_size:
                self._flush_linearizable()
                self.linearizable_timer.reset()
        elif self.scheme.kind == "adaptive":
            if not self._adaptive_inflight:
                self._flush_linearizable()

    def _handle_sequential(self, src: Address,
                           request: SequentialReadRequest) -> None:
        if self.scheme.kind == "adaptive":
            self.logger.fatal(
                "adaptive batching cannot serve sequential reads")
        self.sequential_slot = max(self.sequential_slot, request.slot)
        self.sequential_batch.append(request.command)
        if self.scheme.kind == "size" \
                and len(self.sequential_batch) >= self.scheme.batch_size:
            self._flush_sequential()
            self.sequential_timer.reset()

    def _handle_eventual(self, src: Address,
                         request: EventualReadRequest) -> None:
        if self.scheme.kind == "adaptive":
            self.logger.fatal(
                "adaptive batching cannot serve eventual reads")
        self.eventual_batch.append(request.command)
        if self.scheme.kind == "size" \
                and len(self.eventual_batch) >= self.scheme.batch_size:
            self._flush_eventual()
            self.eventual_timer.reset()

    def _handle_batch_max_slot_reply(self, src: Address,
                                     reply: BatchMaxSlotReply) -> None:
        replies = self.batch_max_slot_replies.get(reply.read_batcher_id)
        if replies is None:
            return
        replies[(reply.group_index, reply.acceptor_index)] = reply.slot
        if not self.config.flexible:
            if len(replies) < self.config.f + 1:
                return
        else:
            flat = {g * self._row_size + i for g, i in replies}
            if not self.grid.is_superset_of_read_quorum(flat):
                return
        max_slot = max(replies.values())
        if self.config.flexible:
            slot = max_slot
        else:
            slot = max_slot + self.config.num_acceptor_groups - 1
        batch = self.pending_linearizable.pop(reply.read_batcher_id)
        del self.batch_max_slot_replies[reply.read_batcher_id]
        self.send(self._random_replica(),
                  ReadRequestBatch(slot=slot, commands=tuple(batch)))
        self._adaptive_inflight = False
        # Adaptive: immediately launch the next batch if reads queued up.
        if self.scheme.kind == "adaptive" and self.linearizable_batch:
            self._flush_linearizable()
