"""Binary codecs for the UnanimousBPaxos hot path.

Dependencies here are plain frozensets of vertex ids (no prefix
compaction -- unanimous fast quorums keep them small), packed as
``[u32 n][n x (i32 leader, i64 id)]``. Commands reuse the BPaxos
command helper (same Command class).
"""

from __future__ import annotations

import struct

from frankenpaxos_tpu.protocols import unanimousbpaxos as m
from frankenpaxos_tpu.protocols.multipaxos.wire import _put_bytes, _take_bytes
from frankenpaxos_tpu.protocols.simplebpaxos.messages import NOOP, Noop
from frankenpaxos_tpu.protocols.simplebpaxos.wire import (
    _put_command,
    _put_vertex,
    _take_command,
    _take_vertex,
)
from frankenpaxos_tpu.runtime.serializer import MessageCodec, register_codec

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_I64I64 = struct.Struct("<qq")


def _put_dep_set(out: bytearray, deps: frozenset) -> None:
    """[i32 n][n x vertex], reusing the shared vertex layout."""
    out += _I32.pack(len(deps))
    for vertex_id in sorted(deps):
        _put_vertex(out, vertex_id)


def _take_dep_set(buf: bytes, at: int):
    (n,) = _I32.unpack_from(buf, at)
    at += 4
    deps = []
    for _ in range(n):
        vertex_id, at = _take_vertex(buf, at)
        deps.append(vertex_id)
    return frozenset(deps), at


def _put_vote_value(out: bytearray, value: m.VoteValue) -> None:
    if isinstance(value.command_or_noop, Noop):
        out.append(0)
    else:
        out.append(1)
        _put_command(out, value.command_or_noop)
    _put_dep_set(out, value.dependencies)


def _take_vote_value(buf: bytes, at: int):
    kind = buf[at]
    at += 1
    if kind == 0:
        command = NOOP
    else:
        command, at = _take_command(buf, at)
    deps, at = _take_dep_set(buf, at)
    return m.VoteValue(command, deps), at


class _VertexValueCodec(MessageCodec):
    """Shared (vertex_id, VoteValue) layout: FastProposal and Commit
    are both message_type(vertex_id, value)."""

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        _put_vote_value(out, message.value)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        value, at = _take_vote_value(buf, at)
        return self.message_type(vertex_id, value), at


class UClientRequestCodec(MessageCodec):
    message_type = m.ClientRequest
    tag = 29

    def encode(self, out, message):
        _put_command(out, message.command)

    def decode(self, buf, at):
        command, at = _take_command(buf, at)
        return m.ClientRequest(command), at


class UDependencyRequestCodec(MessageCodec):
    message_type = m.DependencyRequest
    tag = 30

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        _put_command(out, message.command)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        command, at = _take_command(buf, at)
        return m.DependencyRequest(vertex_id, command), at


class UFastProposalCodec(_VertexValueCodec):
    message_type = m.FastProposal
    tag = 31


class UPhase2bFastCodec(MessageCodec):
    message_type = m.Phase2bFast
    tag = 32

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I32.pack(message.acceptor_id)
        _put_vote_value(out, message.vote_value)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (acceptor,) = _I32.unpack_from(buf, at)
        value, at = _take_vote_value(buf, at + 4)
        return m.Phase2bFast(vertex_id, acceptor, value), at


class UPhase2aCodec(MessageCodec):
    message_type = m.Phase2a
    tag = 33

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I64.pack(message.round)
        _put_vote_value(out, message.vote_value)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        (round,) = _I64.unpack_from(buf, at)
        value, at = _take_vote_value(buf, at + 8)
        return m.Phase2a(vertex_id, round, value), at


class UPhase2bClassicCodec(MessageCodec):
    message_type = m.Phase2bClassic
    tag = 34

    def encode(self, out, message):
        _put_vertex(out, message.vertex_id)
        out += _I64I64.pack(message.acceptor_id, message.round)

    def decode(self, buf, at):
        vertex_id, at = _take_vertex(buf, at)
        acceptor, round = _I64I64.unpack_from(buf, at)
        return m.Phase2bClassic(vertex_id, acceptor, round), at + 16


class UCommitCodec(_VertexValueCodec):
    message_type = m.Commit
    tag = 35


class UClientReplyCodec(MessageCodec):
    message_type = m.ClientReply
    tag = 36

    def encode(self, out, message):
        out += _I64I64.pack(message.client_pseudonym, message.client_id)
        _put_bytes(out, message.result)

    def decode(self, buf, at):
        pseudonym, id = _I64I64.unpack_from(buf, at)
        result, at = _take_bytes(buf, at + 16)
        return m.ClientReply(pseudonym, id, result), at


for _codec in (UClientRequestCodec(), UDependencyRequestCodec(),
               UFastProposalCodec(), UPhase2bFastCodec(),
               UPhase2aCodec(), UPhase2bClassicCodec(), UCommitCodec(),
               UClientReplyCodec()):
    register_codec(_codec)
