"""Echo: the hello-world protocol exercising the runtime contract.

Reference behavior: echo/ (echo/Echo.proto, echo/Server.scala,
echo/Client.scala): a client sends a string, the server echoes it back;
the client counts replies and can ping periodically.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport


@dataclasses.dataclass(frozen=True)
class EchoRequest:
    msg: str


@dataclasses.dataclass(frozen=True)
class EchoReply:
    msg: str


class EchoServer(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger):
        super().__init__(address, transport, logger)
        self.num_messages_received = 0

    def receive(self, src: Address, message: EchoRequest) -> None:
        self.num_messages_received += 1
        self.logger.debug(f"echoing {message.msg!r} to {src}")
        self.send(src, EchoReply(msg=message.msg))


class EchoClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, server_address: Address,
                 ping_period_s: float = 1.0):
        super().__init__(address, transport, logger)
        self.server_address = server_address
        self.num_messages_received = 0
        self._callbacks: list[Callable[[str], None]] = []
        self.ping_timer = self.timer("ping", ping_period_s, self._ping)

    def _ping(self) -> None:
        self.send(self.server_address, EchoRequest(msg="ping"))
        self.ping_timer.start()

    def echo(self, msg: str,
             callback: Optional[Callable[[str], None]] = None) -> None:
        if callback is not None:
            self._callbacks.append(callback)
        self.send(self.server_address, EchoRequest(msg=msg))

    def receive(self, src: Address, message: EchoReply) -> None:
        self.num_messages_received += 1
        if self._callbacks:
            self._callbacks.pop(0)(message.msg)


# Importing for side effect: registers this protocol's binary wire
# codecs with the default serializer (see baseline_wire.py).
from frankenpaxos_tpu.protocols import baseline_wire  # noqa: E402,F401
