"""Vanilla (coupled) Mencius: one Server role with skips and revocation.

Reference behavior: vanillamencius/ (Server.scala:36-1180, Config.scala:
2f+1 servers + mirrored heartbeats). Every server owns the slots
congruent to its index. A client request is voted locally and Phase2a'd
to the others in round 0 ("simple consensus" per slot). Key mechanics:

  * skips (Server.scala:668-700): when a server learns of a slot beyond
    its frontier, it chooses noops in all its owned slots up to it and
    lazily broadcasts the skipped range (piggybacked on the next Phase2a
    or flushed by a timer);
  * revocation (Server.scala:390-430): if the heartbeat declares a
    server dead and its unchosen frontier lags, a peer revokes a range
    of the dead server's slots: Phase1a over the range in a round it
    owns, then proposes the highest votes / noops;
  * execution: in-order executeLog with a client table; only the slot
    owner replies.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional, Union

from frankenpaxos_tpu.heartbeat import HeartbeatOptions, HeartbeatParticipant
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.runtime import Actor, Logger
from frankenpaxos_tpu.runtime.transport import Address, Transport
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.utils import BufferMap


@dataclasses.dataclass(frozen=True)
class VanillaMenciusConfig:
    f: int
    server_addresses: tuple
    heartbeat_addresses: tuple

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.server_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 servers")
        if len(self.heartbeat_addresses) != len(self.server_addresses):
            raise ValueError("heartbeats must mirror servers")


@dataclasses.dataclass(frozen=True)
class CommandId:
    client_address: Address
    client_pseudonym: int
    client_id: int


@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@dataclasses.dataclass(frozen=True)
class Noop:
    pass


NOOP = Noop()
CommandOrNoop = Union[Command, Noop]


@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    result: bytes


@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    start_slot_inclusive: int
    stop_slot_exclusive: int


@dataclasses.dataclass(frozen=True)
class PendingSlotInfo:
    vote_round: int
    vote_value: CommandOrNoop


@dataclasses.dataclass(frozen=True)
class ChosenSlotInfo:
    value: CommandOrNoop
    is_revocation: bool


@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    info: Union[PendingSlotInfo, ChosenSlotInfo]


@dataclasses.dataclass(frozen=True)
class Phase1b:
    server_index: int
    round: int
    start_slot_inclusive: int
    stop_slot_exclusive: int
    info: tuple[Phase1bSlotInfo, ...]


@dataclasses.dataclass(frozen=True)
class Phase2a:
    sending_server: int
    slot: int
    round: int
    value: CommandOrNoop


@dataclasses.dataclass(frozen=True)
class Skip:
    server_index: int
    start_slot_inclusive: int
    stop_slot_exclusive: int


@dataclasses.dataclass(frozen=True)
class Phase2b:
    server_index: int
    slot: int
    round: int


@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: CommandOrNoop
    is_revocation: bool


@dataclasses.dataclass(frozen=True)
class Phase1Nack:
    start_slot_inclusive: int
    stop_slot_exclusive: int
    round: int


@dataclasses.dataclass(frozen=True)
class Phase2Nack:
    slot: int
    round: int


# Log entries (Server.scala:207-230).
@dataclasses.dataclass
class VotelessEntry:
    round: int


@dataclasses.dataclass
class PendingEntry:
    round: int
    vote_round: int
    vote_value: CommandOrNoop


@dataclasses.dataclass
class ChosenEntry:
    value: CommandOrNoop
    is_revocation: bool


@dataclasses.dataclass
class _Phase1State:
    start_slot_inclusive: int
    stop_slot_exclusive: int
    round: int
    phase1bs: dict[int, Phase1b]
    resend: object


@dataclasses.dataclass
class _Phase2State:
    round: int
    value: CommandOrNoop
    is_revocation: bool
    phase2bs: set[int]


class VanillaMenciusServer(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: VanillaMenciusConfig,
                 state_machine: StateMachine, beta: int = 10,
                 revoke_min_period_s: float = 30.0,
                 revoke_max_period_s: float = 60.0,
                 flush_skip_slots_period_s: float = 1.0,
                 resend_phase1as_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.beta = beta
        self.resend_phase1as_period_s = resend_phase1as_period_s
        self.index = list(config.server_addresses).index(address)
        self.other_servers = [a for a in config.server_addresses
                              if a != address]
        n = len(config.server_addresses)
        self.slot_system = ClassicRoundRobin(n)
        self.round_system = ClassicRoundRobin(n)
        self.log: BufferMap = BufferMap()
        self.executed_watermark = 0
        self.client_table: dict[tuple, tuple[int, bytes]] = {}
        self.next_slot = self.slot_system.next_classic_round(self.index, -1)
        self.skip_slots: Optional[tuple[int, int]] = None
        self.recover_round = self.round_system.next_classic_round(
            self.index, 0)
        self.phase1s: dict[int, _Phase1State] = {}
        self.phase2s: dict[int, _Phase2State] = {}
        self.largest_chosen_prefix_slots = [-1] * n

        self.heartbeat = HeartbeatParticipant(
            config.heartbeat_addresses[self.index], transport, logger,
            list(config.heartbeat_addresses), HeartbeatOptions())
        self.flush_skip_slots_timer = self.timer(
            "flushSkipSlots", flush_skip_slots_period_s, self._flush_skips)
        self.revocation_timers = {}
        for i in range(n):
            if i != self.index:
                self.revocation_timers[i] = self._make_revocation_timer(
                    i, revoke_min_period_s, revoke_max_period_s)

    # --- helpers ----------------------------------------------------------
    def _make_revocation_timer(self, revoked: int, min_s: float,
                               max_s: float) -> object:
        def fire():
            first_unchosen = self.slot_system.next_classic_round(
                revoked, self.largest_chosen_prefix_slots[revoked])
            alive = self.heartbeat.unsafe_alive()
            if self.config.heartbeat_addresses[revoked] in alive:
                timer.start()
            elif first_unchosen >= self.next_slot + self.beta:
                timer.start()
            else:
                self._start_revocation(revoked, first_unchosen,
                                       self.next_slot + 2 * self.beta)
                # Timer restarts when the revocation finishes.

        timer = self.timer(f"revocation-{revoked}",
                           self.rng.uniform(min_s, max_s), fire)
        timer.start()
        return timer

    def _start_revocation(self, revoked: int, start: int, stop: int) -> None:
        phase1a = Phase1a(round=self.recover_round,
                          start_slot_inclusive=start,
                          stop_slot_exclusive=stop)
        for server in self.config.server_addresses:
            self.send(server, phase1a)

        def resend():
            for server in self.config.server_addresses:
                self.send(server, phase1a)
            timer.start()

        timer = self.timer(f"resendPhase1as-{revoked}",
                           self.resend_phase1as_period_s, resend)
        timer.start()
        self.phase1s[revoked] = _Phase1State(
            start_slot_inclusive=start, stop_slot_exclusive=stop,
            round=self.recover_round, phase1bs={}, resend=timer)
        self.recover_round = self.round_system.next_classic_round(
            self.index, self.recover_round)

    def _flush_skips(self) -> None:
        if self.skip_slots is None:
            return
        start, stop = self.skip_slots
        for server in self.other_servers:
            self.send(server, Skip(server_index=self.index,
                                   start_slot_inclusive=start,
                                   stop_slot_exclusive=stop))
        self.skip_slots = None

    def _is_chosen(self, slot: int) -> bool:
        return isinstance(self.log.get(slot), ChosenEntry)

    def _advance_with_skips(self, slot: int) -> None:
        """Advance our frontier past ``slot``, choosing noops in our owned
        slots along the way (Server.scala:668-700)."""
        if self.next_slot > slot:
            return
        new_stop = slot + 1 if self.slot_system.leader(slot) == self.index \
            else slot
        if self.skip_slots is None:
            self.flush_skip_slots_timer.start()
            self.skip_slots = (self.next_slot, new_stop)
        else:
            self.skip_slots = (self.skip_slots[0], new_stop)
        while self.next_slot < new_stop:
            self.log.put(self.next_slot,
                         ChosenEntry(NOOP, is_revocation=False))
            self.next_slot = self.slot_system.next_classic_round(
                self.index, self.next_slot)

    def _choose(self, slot: int, value: CommandOrNoop,
                is_revocation: bool) -> None:
        self.log.put(slot, ChosenEntry(value, is_revocation))
        self.phase2s.pop(slot, None)
        owner = self.slot_system.leader(slot)
        if owner != self.index:
            frontier = self.slot_system.next_classic_round(
                owner, self.largest_chosen_prefix_slots[owner])
            while self._is_chosen(frontier):
                self.largest_chosen_prefix_slots[owner] = frontier
                frontier = self.slot_system.next_classic_round(owner,
                                                               frontier)

    def _execute_command(self, slot: int, command: Command,
                         reply_if: Callable[[int], bool]) -> None:
        cid = command.command_id
        key = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(key)
        if cached is not None and cid.client_id < cached[0]:
            return
        if cached is not None and cid.client_id == cached[0]:
            self.send(cid.client_address,
                      ClientReply(command_id=cid, result=cached[1]))
            return
        result = self.state_machine.run(command.command)
        self.client_table[key] = (cid.client_id, result)
        if reply_if(slot):
            self.send(cid.client_address,
                      ClientReply(command_id=cid, result=result))

    def _execute_log(self, reply_if: Callable[[int], bool]) -> None:
        while True:
            entry = self.log.get(self.executed_watermark)
            if not isinstance(entry, ChosenEntry):
                return
            slot = self.executed_watermark
            self.executed_watermark += 1
            if isinstance(entry.value, Command):
                self._execute_command(slot, entry.value, reply_if)

    def _reply_if_mine(self, slot: int) -> bool:
        return self.slot_system.leader(slot) == self.index

    # --- handlers ---------------------------------------------------------
    def receive(self, src: Address, message) -> None:
        handlers = {
            ClientRequest: self._handle_client_request,
            Phase1a: self._handle_phase1a,
            Phase1b: self._handle_phase1b,
            Phase2a: self._handle_phase2a,
            Phase2b: self._handle_phase2b,
            Skip: self._handle_skip,
            Chosen: self._handle_chosen,
            Phase1Nack: self._handle_phase1_nack,
            Phase2Nack: self._handle_phase2_nack,
        }
        handler = handlers.get(type(message))
        if handler is None:
            self.logger.fatal(f"unexpected server message {message!r}")
        handler(src, message)

    def _handle_client_request(self, src: Address,
                               request: ClientRequest) -> None:
        """(Server.scala:767-830)."""
        value = request.command
        self.log.put(self.next_slot,
                     PendingEntry(round=0, vote_round=0, vote_value=value))
        self._flush_skips()
        self.flush_skip_slots_timer.stop()
        phase2a = Phase2a(sending_server=self.index, slot=self.next_slot,
                          round=0, value=value)
        for server in self.other_servers:
            self.send(server, phase2a)
        self.phase2s[self.next_slot] = _Phase2State(
            round=0, value=value, is_revocation=False,
            phase2bs={self.index})
        self.next_slot = self.slot_system.next_classic_round(
            self.index, self.next_slot)

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        """(Server.scala:831-915)."""
        revoked = self.slot_system.leader(phase1a.start_slot_inclusive)
        if revoked == self.index:
            # Someone thinks we're dead; fill our slots so every revoked
            # entry holds something.
            self._advance_with_skips(phase1a.stop_slot_exclusive - 1)
            self._execute_log(self._reply_if_mine)
        infos: list[Phase1bSlotInfo] = []
        slot = phase1a.start_slot_inclusive
        while slot < phase1a.stop_slot_exclusive:
            entry = self.log.get(slot)
            if entry is None:
                self.log.put(slot, VotelessEntry(phase1a.round))
            elif isinstance(entry, VotelessEntry):
                if phase1a.round < entry.round:
                    self.send(src, Phase1Nack(
                        phase1a.start_slot_inclusive,
                        phase1a.stop_slot_exclusive, entry.round))
                    return
                self.log.put(slot, VotelessEntry(phase1a.round))
            elif isinstance(entry, PendingEntry):
                if phase1a.round < entry.round:
                    self.send(src, Phase1Nack(
                        phase1a.start_slot_inclusive,
                        phase1a.stop_slot_exclusive, entry.round))
                    return
                infos.append(Phase1bSlotInfo(slot, PendingSlotInfo(
                    entry.vote_round, entry.vote_value)))
                entry.round = phase1a.round
            else:
                infos.append(Phase1bSlotInfo(slot, ChosenSlotInfo(
                    entry.value, entry.is_revocation)))
            slot = self.slot_system.next_classic_round(revoked, slot)
        self.send(src, Phase1b(
            server_index=self.index, round=phase1a.round,
            start_slot_inclusive=phase1a.start_slot_inclusive,
            stop_slot_exclusive=phase1a.stop_slot_exclusive,
            info=tuple(infos)))

    def _handle_phase1b(self, src: Address, phase1b: Phase1b) -> None:
        """(Server.scala:916-1000)."""
        revoked = self.slot_system.leader(phase1b.start_slot_inclusive)
        phase1 = self.phase1s.get(revoked)
        if phase1 is None or phase1b.round != phase1.round:
            return
        phase1.phase1bs[phase1b.server_index] = phase1b
        if len(phase1.phase1bs) < self.config.f + 1:
            return
        slot = phase1.start_slot_inclusive
        while slot < phase1.stop_slot_exclusive:
            infos = [i.info for p in phase1.phase1bs.values()
                     for i in p.info if i.slot == slot]
            chosen = [i for i in infos if isinstance(i, ChosenSlotInfo)]
            pending = [i for i in infos if isinstance(i, PendingSlotInfo)]
            if chosen:
                self._choose(slot, chosen[0].value, chosen[0].is_revocation)
                if not chosen[0].is_revocation:
                    self._advance_with_skips(slot)
            elif not pending:
                self._propose(phase1.round, slot, NOOP)
            else:
                best = max(pending, key=lambda i: i.vote_round)
                self._propose(phase1.round, slot, best.vote_value)
            slot = self.slot_system.next_classic_round(revoked, slot)
        self._execute_log(lambda _: False)
        phase1.resend.stop()
        del self.phase1s[revoked]
        self.revocation_timers[revoked].start()

    def _propose(self, round: int, slot: int, value: CommandOrNoop) -> None:
        """Revocation proposal (Server.scala:620-668)."""
        self.logger.check_ne(self.index, self.slot_system.leader(slot))
        if slot in self.phase2s:
            return
        entry = self.log.get(slot)
        if isinstance(entry, ChosenEntry):
            return
        if isinstance(entry, (VotelessEntry, PendingEntry)) \
                and round < entry.round:
            return
        self.log.put(slot, PendingEntry(round=round, vote_round=round,
                                        vote_value=value))
        for server in self.other_servers:
            self.send(server, Phase2a(sending_server=self.index, slot=slot,
                                      round=round, value=value))
        self.phase2s[slot] = _Phase2State(round=round, value=value,
                                          is_revocation=True,
                                          phase2bs={self.index})

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        """(Server.scala:1000-1062)."""
        owner = self.slot_system.leader(phase2a.slot)
        if owner == self.index:
            self._advance_with_skips(phase2a.slot)
            self._execute_log(self._reply_if_mine)
        entry = self.log.get(phase2a.slot)
        if isinstance(entry, ChosenEntry):
            self.send(src, Chosen(slot=phase2a.slot, value=entry.value,
                                  is_revocation=entry.is_revocation))
            return
        round = -1 if entry is None else entry.round
        if phase2a.round < round:
            self.send(src, Phase2Nack(slot=phase2a.slot, round=round))
            return
        self.log.put(phase2a.slot,
                     PendingEntry(round=phase2a.round,
                                  vote_round=phase2a.round,
                                  vote_value=phase2a.value))
        if owner != self.index and owner == phase2a.sending_server:
            self._advance_with_skips(phase2a.slot)
            self._execute_log(self._reply_if_mine)
        self._flush_skips()
        self.flush_skip_slots_timer.stop()
        self.send(src, Phase2b(server_index=self.index, slot=phase2a.slot,
                               round=phase2a.round))

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        """(Server.scala:1063-1110)."""
        if isinstance(self.log.get(phase2b.slot), ChosenEntry):
            return
        phase2 = self.phase2s.get(phase2b.slot)
        if phase2 is None or phase2b.round < phase2.round:
            return
        self.logger.check_eq(phase2b.round, phase2.round)
        phase2.phase2bs.add(phase2b.server_index)
        if len(phase2.phase2bs) < self.config.f + 1:
            return
        for server in self.other_servers:
            self.send(server, Chosen(slot=phase2b.slot, value=phase2.value,
                                     is_revocation=phase2.is_revocation))
        self._choose(phase2b.slot, phase2.value, phase2.is_revocation)
        self._execute_log(self._reply_if_mine)

    def _handle_skip(self, src: Address, skip: Skip) -> None:
        slot = skip.start_slot_inclusive
        coordinator = self.slot_system.leader(skip.start_slot_inclusive)
        while slot < skip.stop_slot_exclusive:
            self._choose(slot, NOOP, is_revocation=False)
            slot = self.slot_system.next_classic_round(coordinator, slot)
        self._execute_log(self._reply_if_mine)

    def _handle_chosen(self, src: Address, chosen: Chosen) -> None:
        owner = self.slot_system.leader(chosen.slot)
        if owner == self.index and not chosen.is_revocation:
            self._advance_with_skips(chosen.slot)
        self._choose(chosen.slot, chosen.value, chosen.is_revocation)
        self._execute_log(self._reply_if_mine)

    def _handle_phase1_nack(self, src: Address, nack: Phase1Nack) -> None:
        revoked = self.slot_system.leader(nack.start_slot_inclusive)
        phase1 = self.phase1s.pop(revoked, None)
        if phase1 is None:
            return
        phase1.resend.stop()
        self.recover_round = self.round_system.next_classic_round(
            self.index, max(self.recover_round, nack.round))
        self.revocation_timers[revoked].start()

    def _handle_phase2_nack(self, src: Address, nack: Phase2Nack) -> None:
        phase2 = self.phase2s.pop(nack.slot, None)
        if phase2 is None:
            return
        self.recover_round = self.round_system.next_classic_round(
            self.index, max(self.recover_round, nack.round))


@dataclasses.dataclass
class _Pending:
    id: int
    command: bytes
    callback: Callable[[bytes], None]
    resend: object


class VanillaMenciusClient(Actor):
    def __init__(self, address: Address, transport: Transport,
                 logger: Logger, config: VanillaMenciusConfig,
                 resend_period_s: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period_s = resend_period_s
        self.ids: dict[int, int] = {}
        self.pending: dict[int, _Pending] = {}

    def write(self, pseudonym: int, command: bytes,
              callback: Optional[Callable[[bytes], None]] = None) -> None:
        if pseudonym in self.pending:
            raise RuntimeError(f"pseudonym {pseudonym} has a pending op")
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(Command(
            CommandId(self.address, pseudonym, id), command))
        server = self.config.server_addresses[
            self.rng.randrange(len(self.config.server_addresses))]
        self.send(server, request)

        def resend():
            target = self.config.server_addresses[
                self.rng.randrange(len(self.config.server_addresses))]
            self.send(target, request)
            timer.start()

        timer = self.timer(f"resend-{pseudonym}", self.resend_period_s,
                           resend)
        timer.start()
        self.pending[pseudonym] = _Pending(id, command,
                                           callback or (lambda _: None),
                                           timer)
        self.ids[pseudonym] = id + 1

    def receive(self, src: Address, message) -> None:
        if not isinstance(message, ClientReply):
            self.logger.fatal(f"unexpected client message {message!r}")
        pending = self.pending.get(message.command_id.client_pseudonym)
        if pending is None or pending.id != message.command_id.client_id:
            return
        pending.resend.stop()
        del self.pending[message.command_id.client_pseudonym]
        pending.callback(message.result)

# Importing registers this protocol's binary codecs with the hybrid
# serializer (see vanillamencius_wire.py).
from frankenpaxos_tpu.protocols import vanillamencius_wire  # noqa: E402,F401
