"""Table-driven deployment registry: every protocol over TcpTransport.

The analog of the reference's 105 ``<Role>Main`` objects
(jvm/src/main/scala/frankenpaxos/<proto>/<Role>Main.scala) collapsed
into one registry. For each protocol it knows how to

  * parse a cluster-config JSON into the protocol's Config dataclass
    (the prototext analog; ConfigUtil.scala:7-43),
  * construct every role actor from ``(role, index)`` plus per-role
    ``--options.*`` overrides (LeaderMain.scala:52-80),
  * construct a client and drive one smoke command through it
    (scripts/benchmark_smoke.sh semantics),
  * generate a localhost cluster placement for tests/benchmarks.

Role option overrides are uniform: ``--options.<name>=<value>`` matches
either a keyword parameter of the role constructor or a field of its
options dataclass, coerced to the type of the declared default.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable


def _addr(x) -> tuple:
    return (x[0], int(x[1]))


def _addrs(xs) -> list:
    return [_addr(x) for x in xs]


def coerce(text: str, default: Any) -> Any:
    """Parse ``text`` to the type of ``default`` (bool/int/float/str)."""
    if isinstance(default, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(text)
    if isinstance(default, float):
        return float(text)
    return text


def ctor_kwargs(fn: Callable, overrides: dict) -> dict:
    """Overrides matching ``fn``'s defaulted keyword params, coerced."""
    out = {}
    params = inspect.signature(fn).parameters
    for name, value in overrides.items():
        p = params.get(name)
        if p is None or p.default is inspect.Parameter.empty \
                or p.default is None or dataclasses.is_dataclass(p.default):
            continue
        out[name] = coerce(value, p.default)
    return out


def options_obj(cls, overrides: dict, **fixed):
    """An options dataclass from defaults + matching overrides."""
    base = cls(**fixed)
    repl = {}
    for f in dataclasses.fields(cls):
        if f.name in fixed or f.name not in overrides:
            continue
        default = getattr(base, f.name)
        if dataclasses.is_dataclass(default) or default is None:
            continue
        repl[f.name] = coerce(overrides[f.name], default)
    return dataclasses.replace(base, **repl) if repl else base


@dataclasses.dataclass
class DeployCtx:
    """Everything a role constructor might need."""

    config: Any
    transport: Any
    logger: Any
    overrides: dict
    seed: int = 0
    state_machine: str = "AppendLog"
    collectors: Any = None  # monitoring.Collectors; None -> fakes
    # Durability root (--wal_dir): when set, WAL-capable roles get a
    # Wal over FileStorage at <wal_dir>/<label> and recover from it on
    # construction -- a SIGKILL'd role relaunched with the same
    # wal_dir rejoins with its promises/votes/SM state intact.
    wal_dir: Any = None
    # paxchaos (--fault_fsync "every:stall_s:seed"): wrap this role's
    # WAL storage in a BLOCKING FsyncStallStorage -- the deployed twin
    # of the scenario matrix's storage-fault arm (faults/,
    # wal/faults.py). None (the default) leaves the WAL path
    # completely untouched.
    wal_fault: Any = None
    consumed: set = dataclasses.field(default_factory=set)

    def sm(self):
        from frankenpaxos_tpu.statemachine import state_machine_by_name

        return state_machine_by_name(self.state_machine)

    def wal(self, label: str):
        """A per-role Wal (or None when durability is off)."""
        if not self.wal_dir:
            return None
        import os

        from frankenpaxos_tpu.wal import FileStorage, Wal

        storage = FileStorage(os.path.join(self.wal_dir, label))
        if self.wal_fault:
            from frankenpaxos_tpu.wal import FsyncStallStorage

            parts = self.wal_fault.split(":")
            if parts[0] == "P" and len(parts) == 3:
                # Periodic windows on the host wall clock -- aligned
                # across every role process on the machine.
                storage = FsyncStallStorage(
                    storage, label=label,
                    stall_period_s=float(parts[1]),
                    stall_window_s=float(parts[2]), blocking=True)
            elif parts[0] == "C" and len(parts) == 4:
                storage = FsyncStallStorage(
                    storage, seed=int(parts[3]), label=label,
                    stall_every=int(parts[1]),
                    stall_s=float(parts[2]), blocking=True)
            else:
                raise ValueError(
                    "--fault_fsync spec must be P:<period_s>:"
                    "<window_s> or C:<every>:<stall_s>:<seed>; "
                    f"got {self.wal_fault!r}")
        return Wal(storage)

    def kw(self, fn) -> dict:
        out = ctor_kwargs(fn, self.overrides)
        self.consumed.update(out)
        return out

    def opts(self, cls, **fixed):
        obj = options_obj(cls, self.overrides, **fixed)
        names = {f.name for f in dataclasses.fields(cls)}
        self.consumed.update(names & set(self.overrides))
        return obj

    def opt(self, name: str, default: str) -> str:
        if name in self.overrides:
            self.consumed.add(name)
            return self.overrides[name]
        return default

    def unmatched_overrides(self) -> list:
        return sorted(set(self.overrides) - self.consumed)


@dataclasses.dataclass(frozen=True)
class Role:
    """One deployable role: its addresses in the config + constructor."""

    addresses: Callable[[Any], list]
    make: Callable[[DeployCtx, Any, int], Any]


@dataclasses.dataclass(frozen=True)
class Protocol:
    name: str
    load_config: Callable[[dict], Any]
    roles: "dict[str, Role]"
    make_client: Callable[[DeployCtx, Any], Any]
    # drive(client, tag, callback): issue one command; callback fires on
    # completion (with whatever reply type the protocol uses).
    drive: Callable[[Any, int, Callable[..., None]], None]
    cluster: Callable[[int, Callable[[], list]], dict]


# --------------------------------------------------------------------------
# Per-protocol definitions (lazy imports keep CLI startup light).
# --------------------------------------------------------------------------


def _echo() -> Protocol:
    from frankenpaxos_tpu.protocols import echo as m

    class Cfg:
        def __init__(self, raw):
            self.server = _addr(raw["server"])

    return Protocol(
        name="echo",
        load_config=Cfg,
        roles={"server": Role(
            lambda c: [c.server],
            lambda ctx, a, i: m.EchoServer(a, ctx.transport, ctx.logger))},
        make_client=lambda ctx, a: m.EchoClient(
            a, ctx.transport, ctx.logger, ctx.config.server,
            **ctx.kw(m.EchoClient)),
        drive=lambda client, tag, cb: client.echo(f"hello-{tag}", cb),
        cluster=lambda f, port: {"server": port()},
    )


def _unreplicated() -> Protocol:
    from frankenpaxos_tpu.protocols import unreplicated as m

    class Cfg:
        def __init__(self, raw):
            self.server = _addr(raw["server"])

    return Protocol(
        name="unreplicated",
        load_config=Cfg,
        roles={"server": Role(
            lambda c: [c.server],
            lambda ctx, a, i: m.UnreplicatedServer(
                a, ctx.transport, ctx.logger, ctx.sm(),
                **ctx.kw(m.UnreplicatedServer)))},
        make_client=lambda ctx, a: m.UnreplicatedClient(
            a, ctx.transport, ctx.logger, ctx.config.server,
            **ctx.kw(m.UnreplicatedClient)),
        drive=lambda client, tag, cb: client.propose(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {"server": port()},
    )


def _batchedunreplicated() -> Protocol:
    from frankenpaxos_tpu.protocols import batchedunreplicated as m

    def load(raw):
        cfg = m.BatchedUnreplicatedConfig(
            batcher_addresses=tuple(_addrs(raw["batchers"])),
            server_address=_addr(raw["server"]),
            proxy_server_addresses=tuple(_addrs(raw["proxy_servers"])))
        return cfg

    return Protocol(
        name="batchedunreplicated",
        load_config=load,
        roles={
            "batcher": Role(
                lambda c: list(c.batcher_addresses),
                lambda ctx, a, i: m.BatchedUnreplicatedBatcher(
                    a, ctx.transport, ctx.logger, ctx.config,
                    **ctx.kw(m.BatchedUnreplicatedBatcher))),
            "server": Role(
                lambda c: [c.server_address],
                lambda ctx, a, i: m.BatchedUnreplicatedServer(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                    seed=ctx.seed)),
            "proxy_server": Role(
                lambda c: list(c.proxy_server_addresses),
                lambda ctx, a, i: m.BatchedUnreplicatedProxyServer(
                    a, ctx.transport, ctx.logger, ctx.config,
                    **ctx.kw(m.BatchedUnreplicatedProxyServer))),
        },
        make_client=lambda ctx, a: m.BatchedUnreplicatedClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.BatchedUnreplicatedClient)),
        drive=lambda client, tag, cb: client.propose(b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "batchers": [port() for _ in range(2)],
            "server": port(),
            "proxy_servers": [port() for _ in range(2)],
        },
    )


def _single_decree(name, mod_name, cfg_name, leader_name, acceptor_name,
                   client_name, payload) -> Protocol:
    """paxos / fastpaxos / caspaxos / matchmakerpaxos share this shape."""
    import importlib

    m = importlib.import_module(f"frankenpaxos_tpu.protocols.{mod_name}")
    cfg_cls = getattr(m, cfg_name)
    leader_cls = getattr(m, leader_name)
    acceptor_cls = getattr(m, acceptor_name)
    client_cls = getattr(m, client_name)
    has_matchmakers = name == "matchmakerpaxos"

    def load(raw):
        kwargs = dict(
            f=raw["f"],
            leader_addresses=tuple(_addrs(raw["leaders"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])))
        if has_matchmakers:
            kwargs["matchmaker_addresses"] = tuple(
                _addrs(raw["matchmakers"]))
        return cfg_cls(**kwargs)

    roles = {
        "leader": Role(
            lambda c: list(c.leader_addresses),
            lambda ctx, a, i: leader_cls(
                a, ctx.transport, ctx.logger, ctx.config,
                **ctx.kw(leader_cls))),
        "acceptor": Role(
            lambda c: list(c.acceptor_addresses),
            lambda ctx, a, i: acceptor_cls(
                a, ctx.transport, ctx.logger, ctx.config)),
    }
    if has_matchmakers:
        roles["matchmaker"] = Role(
            lambda c: list(c.matchmaker_addresses),
            lambda ctx, a, i: m.Matchmaker(
                a, ctx.transport, ctx.logger, ctx.config))

    def cluster(f, port):
        raw = {
            "f": f,
            "leaders": [port() for _ in range(f + 1)],
            "acceptors": [port() for _ in range(2 * f + 1)],
        }
        if has_matchmakers:
            raw["matchmakers"] = [port() for _ in range(2 * f + 1)]
        return raw

    return Protocol(
        name=name,
        load_config=load,
        roles=roles,
        make_client=lambda ctx, a: client_cls(
            a, ctx.transport, ctx.logger, ctx.config,
            **ctx.kw(client_cls)),
        drive=payload,
        cluster=cluster,
    )


def _paxos() -> Protocol:
    return _single_decree(
        "paxos", "paxos", "PaxosConfig", "PaxosLeader", "PaxosAcceptor",
        "PaxosClient",
        lambda client, tag, cb: client.propose(f"v{tag}", cb))


def _fastpaxos() -> Protocol:
    return _single_decree(
        "fastpaxos", "fastpaxos", "FastPaxosConfig", "FastPaxosLeader",
        "FastPaxosAcceptor", "FastPaxosClient",
        lambda client, tag, cb: client.propose(f"v{tag}", cb))


def _caspaxos() -> Protocol:
    return _single_decree(
        "caspaxos", "caspaxos", "CasPaxosConfig", "CasPaxosLeader",
        "CasPaxosAcceptor", "CasPaxosClient",
        lambda client, tag, cb: client.propose({tag}, cb))


def _matchmakerpaxos() -> Protocol:
    return _single_decree(
        "matchmakerpaxos", "matchmakerpaxos", "MatchmakerPaxosConfig",
        "MatchmakerPaxosLeader", "MatchmakerPaxosAcceptor",
        "MatchmakerPaxosClient",
        lambda client, tag, cb: client.propose(f"v{tag}", cb))


def _make_ingest_batcher(ctx: "DeployCtx", address, index: int,
                         protocol: str):
    """Construct a paxingest disseminator (ingest/) for either run-
    pipeline protocol -- WAL-free by design, so no ctx.wal plumbing."""
    from frankenpaxos_tpu import ingest

    router = (ingest.MultiPaxosIngestRouter(ctx.config)
              if protocol == "multipaxos"
              else ingest.MenciusIngestRouter(ctx.config))
    return ingest.IngestBatcher(
        address, ctx.transport, ctx.logger, router, index=index,
        options=ctx.opts(ingest.IngestBatcherOptions), seed=ctx.seed)


def _multipaxos() -> Protocol:
    from frankenpaxos_tpu.protocols import multipaxos as mp

    def load(raw):
        config = mp.MultiPaxosConfig(
            f=raw["f"],
            batcher_addresses=_addrs(raw.get("batchers", [])),
            ingest_batcher_addresses=_addrs(
                raw.get("ingest_batchers", [])),
            read_batcher_addresses=_addrs(raw.get("read_batchers", [])),
            leader_addresses=_addrs(raw["leaders"]),
            leader_election_addresses=_addrs(raw["leader_elections"]),
            proxy_leader_addresses=_addrs(raw["proxy_leaders"]),
            acceptor_addresses=[_addrs(g) for g in raw["acceptors"]],
            replica_addresses=_addrs(raw["replicas"]),
            proxy_replica_addresses=_addrs(raw.get("proxy_replicas", [])),
            flexible=raw.get("flexible", False),
            distribution_scheme=mp.DistributionScheme(
                raw.get("distribution_scheme", "hash")),
        )
        config.check_valid()
        return config

    def flat_acceptors(c):
        return [a for group in c.acceptor_addresses for a in group]

    return Protocol(
        name="multipaxos",
        load_config=load,
        roles={
            "batcher": Role(
                lambda c: list(c.batcher_addresses),
                lambda ctx, a, i: mp.Batcher(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(mp.BatcherOptions),
                    collectors=ctx.collectors)),
            "read_batcher": Role(
                lambda c: list(c.read_batcher_addresses),
                lambda ctx, a, i: mp.ReadBatcher(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(mp.ReadBatchingScheme), seed=ctx.seed)),
            "ingest_batcher": Role(
                lambda c: list(c.ingest_batcher_addresses),
                lambda ctx, a, i: _make_ingest_batcher(
                    ctx, a, i, "multipaxos")),
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: mp.Leader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(mp.LeaderOptions), seed=ctx.seed,
                    collectors=ctx.collectors)),
            "proxy_leader": Role(
                lambda c: list(c.proxy_leader_addresses),
                lambda ctx, a, i: mp.ProxyLeader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(mp.ProxyLeaderOptions), seed=ctx.seed,
                    collectors=ctx.collectors)),
            "acceptor": Role(
                flat_acceptors,
                lambda ctx, a, i: mp.Acceptor(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(mp.AcceptorOptions),
                    collectors=ctx.collectors,
                    wal=ctx.wal(f"acceptor_{i}"))),
            "replica": Role(
                lambda c: list(c.replica_addresses),
                lambda ctx, a, i: mp.Replica(
                    a, ctx.transport, ctx.logger, ctx.sm(), ctx.config,
                    ctx.opts(mp.ReplicaOptions), seed=ctx.seed,
                    collectors=ctx.collectors,
                    wal=ctx.wal(f"replica_{i}"))),
            "proxy_replica": Role(
                lambda c: list(c.proxy_replica_addresses),
                lambda ctx, a, i: mp.ProxyReplica(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(mp.ProxyReplicaOptions),
                    collectors=ctx.collectors)),
        },
        make_client=lambda ctx, a: mp.Client(
            a, ctx.transport, ctx.logger, ctx.config,
            ctx.opts(mp.ClientOptions), seed=ctx.seed),
        drive=_multipaxos_drive,
        cluster=lambda f, port: {
            "f": f,
            "batchers": [],
            "ingest_batchers": [],
            "read_batchers": [],
            "leaders": [port() for _ in range(f + 1)],
            "leader_elections": [port() for _ in range(f + 1)],
            "proxy_leaders": [port() for _ in range(f + 1)],
            "acceptors": [[port() for _ in range(2 * f + 1)]],
            "replicas": [port() for _ in range(f + 1)],
            "proxy_replicas": [],
        },
    )


def _multipaxos_drive(client, tag, cb):
    from frankenpaxos_tpu.runtime.serializer import PickleSerializer
    from frankenpaxos_tpu.statemachine import SetRequest

    client.write(0, PickleSerializer().to_bytes(
        SetRequest(((f"k{tag}", str(tag)),))), cb)


def _mencius() -> Protocol:
    from frankenpaxos_tpu.protocols import mencius as m

    def load(raw):
        config = m.MenciusConfig(
            f=raw["f"],
            batcher_addresses=_addrs(raw.get("batchers", [])),
            ingest_batcher_addresses=_addrs(
                raw.get("ingest_batchers", [])),
            leader_addresses=[_addrs(g) for g in raw["leaders"]],
            leader_election_addresses=[_addrs(g)
                                       for g in raw["leader_elections"]],
            proxy_leader_addresses=_addrs(raw["proxy_leaders"]),
            acceptor_addresses=[[_addrs(g) for g in grp]
                                for grp in raw["acceptors"]],
            replica_addresses=_addrs(raw["replicas"]),
            proxy_replica_addresses=_addrs(raw.get("proxy_replicas", [])),
            distribution_scheme=m.DistributionScheme(
                raw.get("distribution_scheme", "hash")),
        )
        config.check_valid()
        return config

    def flat_leaders(c):
        return [a for group in c.leader_addresses for a in group]

    def flat_acceptors(c):
        return [a for grp in c.acceptor_addresses for g in grp for a in g]

    return Protocol(
        name="mencius",
        load_config=load,
        roles={
            "batcher": Role(
                lambda c: list(c.batcher_addresses),
                lambda ctx, a, i: m.MenciusBatcher(
                    a, ctx.transport, ctx.logger, ctx.config,
                    seed=ctx.seed, **ctx.kw(m.MenciusBatcher))),
            "ingest_batcher": Role(
                lambda c: list(c.ingest_batcher_addresses),
                lambda ctx, a, i: _make_ingest_batcher(
                    ctx, a, i, "mencius")),
            "leader": Role(
                flat_leaders,
                lambda ctx, a, i: m.MenciusLeader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    seed=ctx.seed, **ctx.kw(m.MenciusLeader))),
            "proxy_leader": Role(
                lambda c: list(c.proxy_leader_addresses),
                lambda ctx, a, i: m.MenciusProxyLeader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    seed=ctx.seed)),
            "acceptor": Role(
                flat_acceptors,
                lambda ctx, a, i: m.MenciusAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config,
                    wal=ctx.wal(f"acceptor_{i}"))),
            "replica": Role(
                lambda c: list(c.replica_addresses),
                lambda ctx, a, i: m.MenciusReplica(
                    a, ctx.transport, ctx.logger, ctx.sm(), ctx.config,
                    seed=ctx.seed, wal=ctx.wal(f"replica_{i}"),
                    **ctx.kw(m.MenciusReplica))),
            "proxy_replica": Role(
                lambda c: list(c.proxy_replica_addresses),
                lambda ctx, a, i: m.MenciusProxyReplica(
                    a, ctx.transport, ctx.logger, ctx.config)),
        },
        make_client=lambda ctx, a: m.MenciusClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.MenciusClient)),
        drive=lambda client, tag, cb: client.write(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "batchers": [],
            "ingest_batchers": [],
            "leaders": [[port() for _ in range(f + 1)]
                        for _ in range(2)],
            "leader_elections": [[port() for _ in range(f + 1)]
                                 for _ in range(2)],
            "proxy_leaders": [port() for _ in range(f + 1)],
            "acceptors": [[[port() for _ in range(2 * f + 1)]]
                          for _ in range(2)],
            "replicas": [port() for _ in range(f + 1)],
            "proxy_replicas": [],
        },
    )


def _vanillamencius() -> Protocol:
    from frankenpaxos_tpu.protocols import vanillamencius as m

    def load(raw):
        return m.VanillaMenciusConfig(
            f=raw["f"],
            server_addresses=tuple(_addrs(raw["servers"])),
            heartbeat_addresses=tuple(_addrs(raw["heartbeats"])))

    return Protocol(
        name="vanillamencius",
        load_config=load,
        roles={"server": Role(
            lambda c: list(c.server_addresses),
            lambda ctx, a, i: m.VanillaMenciusServer(
                a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                seed=ctx.seed, **ctx.kw(m.VanillaMenciusServer)))},
        make_client=lambda ctx, a: m.VanillaMenciusClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.VanillaMenciusClient)),
        drive=lambda client, tag, cb: client.write(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "servers": [port() for _ in range(2 * f + 1)],
            "heartbeats": [port() for _ in range(2 * f + 1)],
        },
    )


def _fastmultipaxos() -> Protocol:
    from frankenpaxos_tpu import roundsystem as rs
    from frankenpaxos_tpu.protocols import fastmultipaxos as m

    def load(raw):
        f = raw["f"]
        name = raw.get("round_system", "round_zero_fast")
        systems = {
            "round_zero_fast": lambda: rs.RoundZeroFast(f + 1),
            "classic_round_robin": lambda: rs.ClassicRoundRobin(f + 1),
            "mixed_round_robin": lambda: rs.MixedRoundRobin(f + 1),
        }
        return m.FastMultiPaxosConfig(
            f=f,
            leader_addresses=tuple(_addrs(raw["leaders"])),
            leader_election_addresses=tuple(
                _addrs(raw["leader_elections"])),
            leader_heartbeat_addresses=tuple(
                _addrs(raw["leader_heartbeats"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])),
            acceptor_heartbeat_addresses=tuple(
                _addrs(raw["acceptor_heartbeats"])),
            round_system=systems[name]())

    return Protocol(
        name="fastmultipaxos",
        load_config=load,
        roles={
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: m.FastMultiPaxosLeader(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                    options=ctx.opts(m.FastMultiPaxosLeaderOptions),
                    seed=ctx.seed)),
            "acceptor": Role(
                lambda c: list(c.acceptor_addresses),
                lambda ctx, a, i: m.FastMultiPaxosAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(m.FastMultiPaxosAcceptorOptions))),
        },
        make_client=lambda ctx, a: m.FastMultiPaxosClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.FastMultiPaxosClient)),
        drive=lambda client, tag, cb: client.propose(b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            # The reference's own committed benchmarks deploy
            # FastMultiPaxos with the classic round-robin round system
            # (benchmarks/fastmultipaxos/smoke.py:17,
            # nsdi_fig1_lt.py:17): concurrent clients proposing
            # directly to acceptors in a fast round vote at offset
            # next_slots and wedge until recovery. Tests exercising the
            # fast path build round_zero_fast configs directly.
            "round_system": "classic_round_robin",
            "leaders": [port() for _ in range(f + 1)],
            "leader_elections": [port() for _ in range(f + 1)],
            "leader_heartbeats": [port() for _ in range(f + 1)],
            "acceptors": [port() for _ in range(2 * f + 1)],
            "acceptor_heartbeats": [port() for _ in range(2 * f + 1)],
        },
    )


def _epaxos() -> Protocol:
    from frankenpaxos_tpu.protocols import epaxos as m

    def load(raw):
        return m.EPaxosConfig(
            f=raw["f"],
            replica_addresses=tuple(_addrs(raw["replicas"])))

    return Protocol(
        name="epaxos",
        load_config=load,
        roles={"replica": Role(
            lambda c: list(c.replica_addresses),
            lambda ctx, a, i: m.EPaxosReplica(
                a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                ctx.opts(m.EPaxosReplicaOptions), seed=ctx.seed))},
        make_client=lambda ctx, a: m.EPaxosClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.EPaxosClient)),
        drive=lambda client, tag, cb: client.propose(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "replicas": [port() for _ in range(2 * f + 1)],
        },
    )


def _simplebpaxos(gc: bool = False) -> Protocol:
    if gc:
        from frankenpaxos_tpu.protocols import simplegcbpaxos as m

        leader_cls, proposer_cls = m.GcBPaxosLeader, m.GcBPaxosProposer
        dep_cls, acceptor_cls = m.GcBPaxosDepServiceNode, m.GcBPaxosAcceptor
        replica_cls = m.GcBPaxosReplica
    else:
        from frankenpaxos_tpu.protocols import simplebpaxos as m

        leader_cls, proposer_cls = m.BPaxosLeader, m.BPaxosProposer
        dep_cls, acceptor_cls = m.BPaxosDepServiceNode, m.BPaxosAcceptor
        replica_cls = m.BPaxosReplica
    from frankenpaxos_tpu.protocols.simplebpaxos import BPaxosClient

    def load(raw):
        kwargs = dict(
            f=raw["f"],
            leader_addresses=tuple(_addrs(raw["leaders"])),
            proposer_addresses=tuple(_addrs(raw["proposers"])),
            dep_service_node_addresses=tuple(_addrs(raw["dep_nodes"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])),
            replica_addresses=tuple(_addrs(raw["replicas"])))
        if gc:
            from frankenpaxos_tpu.protocols.simplegcbpaxos import (
                GcBPaxosConfig,
            )

            return GcBPaxosConfig(
                garbage_collector_addresses=tuple(
                    _addrs(raw["garbage_collectors"])), **kwargs)
        from frankenpaxos_tpu.protocols.simplebpaxos import (
            SimpleBPaxosConfig,
        )

        return SimpleBPaxosConfig(**kwargs)

    roles = {
        "leader": Role(
            lambda c: list(c.leader_addresses),
            lambda ctx, a, i: leader_cls(
                a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
                **ctx.kw(leader_cls))),
        "proposer": Role(
            lambda c: list(c.proposer_addresses),
            lambda ctx, a, i: proposer_cls(
                a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
                **ctx.kw(proposer_cls))),
        "dep_node": Role(
            lambda c: list(c.dep_service_node_addresses),
            lambda ctx, a, i: dep_cls(
                a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                **ctx.kw(dep_cls))),
        "acceptor": Role(
            lambda c: list(c.acceptor_addresses),
            lambda ctx, a, i: acceptor_cls(
                a, ctx.transport, ctx.logger, ctx.config,
                **ctx.kw(acceptor_cls))),
        "replica": Role(
            lambda c: list(c.replica_addresses),
            lambda ctx, a, i: replica_cls(
                a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                seed=ctx.seed, **ctx.kw(replica_cls))),
    }
    if gc:
        roles["garbage_collector"] = Role(
            lambda c: list(c.garbage_collector_addresses),
            lambda ctx, a, i: m.GarbageCollector(
                a, ctx.transport, ctx.logger, ctx.config))

    def cluster(f, port):
        raw = {
            "f": f,
            "leaders": [port() for _ in range(f + 1)],
            "proposers": [port() for _ in range(f + 1)],
            "dep_nodes": [port() for _ in range(2 * f + 1)],
            "acceptors": [port() for _ in range(2 * f + 1)],
            "replicas": [port() for _ in range(f + 1)],
        }
        if gc:
            raw["garbage_collectors"] = [port() for _ in range(f + 1)]
        return raw

    return Protocol(
        name="simplegcbpaxos" if gc else "simplebpaxos",
        load_config=load,
        roles=roles,
        make_client=lambda ctx, a: BPaxosClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(BPaxosClient)),
        drive=lambda client, tag, cb: client.propose(0, b"w%d" % tag, cb),
        cluster=cluster,
    )


def _unanimousbpaxos() -> Protocol:
    from frankenpaxos_tpu.protocols import unanimousbpaxos as m

    def load(raw):
        return m.UnanimousBPaxosConfig(
            f=raw["f"],
            leader_addresses=tuple(_addrs(raw["leaders"])),
            dep_service_node_addresses=tuple(_addrs(raw["dep_nodes"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])))

    return Protocol(
        name="unanimousbpaxos",
        load_config=load,
        roles={
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: m.UnanimousBPaxosLeader(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                    seed=ctx.seed, **ctx.kw(m.UnanimousBPaxosLeader))),
            "dep_node": Role(
                lambda c: list(c.dep_service_node_addresses),
                lambda ctx, a, i: m.UnanimousBPaxosDepServiceNode(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm())),
            "acceptor": Role(
                lambda c: list(c.acceptor_addresses),
                lambda ctx, a, i: m.UnanimousBPaxosAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config)),
        },
        make_client=lambda ctx, a: m.UnanimousBPaxosClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.UnanimousBPaxosClient)),
        drive=lambda client, tag, cb: client.propose(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "leaders": [port() for _ in range(f + 1)],
            "dep_nodes": [port() for _ in range(2 * f + 1)],
            "acceptors": [port() for _ in range(2 * f + 1)],
        },
    )


def _matchmakermultipaxos() -> Protocol:
    from frankenpaxos_tpu.protocols import matchmakermultipaxos as m

    def load(raw):
        return m.MatchmakerMultiPaxosConfig(
            f=raw["f"],
            leader_addresses=tuple(_addrs(raw["leaders"])),
            matchmaker_addresses=tuple(_addrs(raw["matchmakers"])),
            reconfigurer_addresses=tuple(_addrs(raw["reconfigurers"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])),
            replica_addresses=tuple(_addrs(raw["replicas"])))

    return Protocol(
        name="matchmakermultipaxos",
        load_config=load,
        roles={
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: m.MMPLeader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    seed=ctx.seed,
                    quorum_backend=ctx.opt("quorum_backend", "dict"))),
            "matchmaker": Role(
                lambda c: list(c.matchmaker_addresses),
                lambda ctx, a, i: m.MMPMatchmaker(
                    a, ctx.transport, ctx.logger, ctx.config)),
            "reconfigurer": Role(
                lambda c: list(c.reconfigurer_addresses),
                lambda ctx, a, i: m.MMPReconfigurer(
                    a, ctx.transport, ctx.logger, ctx.config,
                    seed=ctx.seed, **ctx.kw(m.MMPReconfigurer))),
            "acceptor": Role(
                lambda c: list(c.acceptor_addresses),
                lambda ctx, a, i: m.MMPAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config)),
            "replica": Role(
                lambda c: list(c.replica_addresses),
                lambda ctx, a, i: m.MMPReplica(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm())),
        },
        make_client=lambda ctx, a: m.MMPClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.MMPClient)),
        drive=lambda client, tag, cb: client.write(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "leaders": [port() for _ in range(f + 1)],
            "matchmakers": [port() for _ in range(2 * f + 1)],
            "reconfigurers": [port()],
            "acceptors": [port() for _ in range(2 * f + 1)],
            "replicas": [port() for _ in range(f + 1)],
        },
    )


def _horizontal() -> Protocol:
    from frankenpaxos_tpu.protocols import horizontal as m

    def load(raw):
        return m.HorizontalConfig(
            f=raw["f"],
            leader_addresses=tuple(_addrs(raw["leaders"])),
            leader_election_addresses=tuple(
                _addrs(raw["leader_elections"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])),
            replica_addresses=tuple(_addrs(raw["replicas"])),
            alpha=raw.get("alpha", 10))

    return Protocol(
        name="horizontal",
        load_config=load,
        roles={
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: m.HorizontalLeader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    seed=ctx.seed)),
            "acceptor": Role(
                lambda c: list(c.acceptor_addresses),
                lambda ctx, a, i: m.HorizontalAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config)),
            "replica": Role(
                lambda c: list(c.replica_addresses),
                lambda ctx, a, i: m.HorizontalReplica(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm())),
        },
        make_client=lambda ctx, a: m.HorizontalClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.HorizontalClient)),
        drive=lambda client, tag, cb: client.write(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "leaders": [port() for _ in range(f + 1)],
            "leader_elections": [port() for _ in range(f + 1)],
            "acceptors": [port() for _ in range(2 * f + 1)],
            "replicas": [port() for _ in range(f + 1)],
            "alpha": 10,
        },
    )


def _fasterpaxos() -> Protocol:
    from frankenpaxos_tpu.protocols import fasterpaxos as m

    def load(raw):
        return m.FasterPaxosConfig(
            f=raw["f"],
            server_addresses=tuple(_addrs(raw["servers"])))

    return Protocol(
        name="fasterpaxos",
        load_config=load,
        roles={"server": Role(
            lambda c: list(c.server_addresses),
            lambda ctx, a, i: m.FasterPaxosServer(
                a, ctx.transport, ctx.logger, ctx.config, ctx.sm(),
                options=ctx.opts(m.FasterPaxosOptions), seed=ctx.seed))},
        make_client=lambda ctx, a: m.FasterPaxosClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.FasterPaxosClient)),
        drive=lambda client, tag, cb: client.write(0, b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "servers": [port() for _ in range(2 * f + 1)],
        },
    )


def _craq() -> Protocol:
    from frankenpaxos_tpu.protocols import craq as m

    def load(raw):
        return m.CraqConfig(
            chain_node_addresses=tuple(_addrs(raw["chain_nodes"])))

    return Protocol(
        name="craq",
        load_config=load,
        roles={"chain_node": Role(
            lambda c: list(c.chain_node_addresses),
            lambda ctx, a, i: m.ChainNode(
                a, ctx.transport, ctx.logger, ctx.config))},
        make_client=lambda ctx, a: m.CraqClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.CraqClient)),
        drive=lambda client, tag, cb: client.write(
            0, f"k{tag}", f"v{tag}", lambda *a: cb(*(a or (None,)))),
        cluster=lambda f, port: {
            "chain_nodes": [port() for _ in range(3)],
        },
    )


def _scalog() -> Protocol:
    from frankenpaxos_tpu.protocols import scalog as m

    def load(raw):
        return m.ScalogConfig(
            f=raw["f"],
            server_addresses=tuple(tuple(_addrs(shard))
                                   for shard in raw["servers"]),
            aggregator_address=_addr(raw["aggregator"]),
            leader_addresses=tuple(_addrs(raw["leaders"])),
            acceptor_addresses=tuple(_addrs(raw["acceptors"])),
            replica_addresses=tuple(_addrs(raw["replicas"])),
            proxy_replica_addresses=tuple(
                _addrs(raw.get("proxy_replicas", []))))

    def flat_servers(c):
        return [a for shard in c.server_addresses for a in shard]

    return Protocol(
        name="scalog",
        load_config=load,
        roles={
            "server": Role(
                flat_servers,
                lambda ctx, a, i: m.ScalogServer(
                    a, ctx.transport, ctx.logger, ctx.config,
                    **ctx.kw(m.ScalogServer))),
            "aggregator": Role(
                lambda c: [c.aggregator_address],
                lambda ctx, a, i: m.ScalogAggregator(
                    a, ctx.transport, ctx.logger, ctx.config,
                    **ctx.kw(m.ScalogAggregator))),
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: m.ScalogLeader(
                    a, ctx.transport, ctx.logger, ctx.config)),
            "acceptor": Role(
                lambda c: list(c.acceptor_addresses),
                lambda ctx, a, i: m.ScalogAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config)),
            "replica": Role(
                lambda c: list(c.replica_addresses),
                lambda ctx, a, i: m.ScalogReplica(
                    a, ctx.transport, ctx.logger, ctx.config, ctx.sm())),
            "proxy_replica": Role(
                lambda c: list(c.proxy_replica_addresses),
                lambda ctx, a, i: m.ScalogProxyReplica(
                    a, ctx.transport, ctx.logger, ctx.config,
                    **ctx.kw(m.ScalogProxyReplica))),
        },
        make_client=lambda ctx, a: m.ScalogClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            **ctx.kw(m.ScalogClient)),
        drive=lambda client, tag, cb: client.propose(b"w%d" % tag, cb),
        cluster=lambda f, port: {
            "f": f,
            "servers": [[port() for _ in range(f + 1)]
                        for _ in range(2)],
            "aggregator": port(),
            "leaders": [port() for _ in range(f + 1)],
            "acceptors": [port() for _ in range(2 * f + 1)],
            "replicas": [port() for _ in range(f + 1)],
            "proxy_replicas": [port() for _ in range(f + 1)],
        },
    )


def _wpaxos() -> Protocol:
    from frankenpaxos_tpu.protocols import wpaxos as m

    def load(raw):
        config = m.WPaxosConfig(
            zones=tuple(raw["zones"]),
            leader_addresses=_addrs(raw["leaders"]),
            acceptor_addresses=tuple(
                tuple(_addrs(row)) for row in raw["acceptors"]),
            replica_addresses=_addrs(raw["replicas"]),
            num_groups=raw.get("num_groups", 4))
        config.check_valid()
        return config

    return Protocol(
        name="wpaxos",
        load_config=load,
        roles={
            "leader": Role(
                lambda c: list(c.leader_addresses),
                lambda ctx, a, i: m.WPaxosLeader(
                    a, ctx.transport, ctx.logger, ctx.config,
                    ctx.opts(m.WPaxosLeaderOptions))),
            "acceptor": Role(
                lambda c: [a for row in c.acceptor_addresses
                           for a in row],
                lambda ctx, a, i: m.WPaxosAcceptor(
                    a, ctx.transport, ctx.logger, ctx.config,
                    wal=ctx.wal(f"acceptor_{i}"))),
            "replica": Role(
                lambda c: list(c.replica_addresses),
                lambda ctx, a, i: m.WPaxosReplica(
                    a, ctx.transport, ctx.logger, ctx.config,
                    **ctx.kw(m.WPaxosReplica))),
        },
        make_client=lambda ctx, a: m.WPaxosClient(
            a, ctx.transport, ctx.logger, ctx.config, seed=ctx.seed,
            options=ctx.opts(m.WPaxosClientOptions)),
        # Pseudonyms rotate so closed-loop drivers can keep several
        # commands in flight; keys spread the load across groups.
        drive=lambda client, tag, cb: client.write(
            tag % 16, b"w%d" % tag, cb, key=b"obj-%d" % (tag % 8)),
        cluster=lambda f, port: {
            "zones": [f"zone-{z}" for z in range(3)],
            "leaders": [port() for _ in range(3)],
            "acceptors": [[port() for _ in range(2 * f + 1)]
                          for _ in range(3)],
            "replicas": [port() for _ in range(3)],
            "num_groups": 4,
        },
    )


REGISTRY: "dict[str, Callable[[], Protocol]]" = {
    "echo": _echo,
    "unreplicated": _unreplicated,
    "batchedunreplicated": _batchedunreplicated,
    "paxos": _paxos,
    "fastpaxos": _fastpaxos,
    "caspaxos": _caspaxos,
    "multipaxos": _multipaxos,
    "mencius": _mencius,
    "vanillamencius": _vanillamencius,
    "fastmultipaxos": _fastmultipaxos,
    "epaxos": _epaxos,
    "simplebpaxos": lambda: _simplebpaxos(gc=False),
    "simplegcbpaxos": lambda: _simplebpaxos(gc=True),
    "unanimousbpaxos": _unanimousbpaxos,
    "matchmakerpaxos": _matchmakerpaxos,
    "matchmakermultipaxos": _matchmakermultipaxos,
    "horizontal": _horizontal,
    "fasterpaxos": _fasterpaxos,
    "craq": _craq,
    "scalog": _scalog,
    "wpaxos": _wpaxos,
}

PROTOCOL_NAMES = sorted(REGISTRY)


def get_protocol(name: str) -> Protocol:
    try:
        factory = REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown protocol {name!r}; known: {PROTOCOL_NAMES}") from None
    return factory()
