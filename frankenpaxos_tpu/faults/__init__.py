"""paxchaos: one deterministic fault plane compiled to two worlds.

``FaultSchedule`` (string-seeded, digest-identified) + the sim and
deployed backends -- see schedule.py for the contract and
docs/GLOBAL.md for the twin methodology.
"""

from frankenpaxos_tpu.faults.deployed_backend import (  # noqa: F401
    DeployedBackend,
    fsync_fault_args,
    link_fault_args,
    LinkFaults,
    parse_link_fault_spec,
    run_wall,
)
from frankenpaxos_tpu.faults.schedule import (  # noqa: F401
    craq_chain_kill_schedule,
    FaultEvent,
    FaultSchedule,
    fsync_stall_schedule,
    ingest_handoff_schedule,
    KINDS,
    ScheduleRunner,
    zone_outage_schedule,
)
from frankenpaxos_tpu.faults.sim_backend import (  # noqa: F401
    SimCraqBackend,
    SimWPaxosBackend,
)
