"""paxchaos: one fault plane, two worlds (the FaultSchedule).

The scenario matrix (PR 13) and the deployed chaos harness (PR 3/9)
inject the SAME fault classes -- role kills, zone outages, fsync
stalls, partitions -- through two completely disjoint sets of ad-hoc
wiring: virtual-time calls sprinkled through ``scenarios/matrix.py``
on one side, SIGKILL helpers hand-sequenced inside deployment tests on
the other. Nothing guaranteed the two worlds ever ran the *same*
fault plan, so no deployed run could be called a twin of a sim row.

This module is the single fault plane: a :class:`FaultSchedule` is a
frozen, string-seeded list of :class:`FaultEvent` rows (time offset,
kind, target, params) that COMPILES TO BOTH BACKENDS --

* the sim world (:mod:`frankenpaxos_tpu.faults.sim_backend`):
  ``GeoSimTransport`` chaos controls, ``GeoTopology`` partitions/
  brownouts, ``wal/faults.FsyncStallStorage`` with the virtual-time
  ``stall_sender`` bridge, harness ``crash_zone``/``restart_zone``;
* the deployed world (:mod:`frankenpaxos_tpu.faults.deployed_backend`):
  ``bench/chaos.py``'s SIGKILL + verbatim-relaunch machinery, SIGSTOP/
  SIGCONT via ``os.kill``, ``FsyncStallStorage`` wrapping a real
  ``FileStorage`` (armed at role launch through the CLI), and latency/
  partition injection at the ``TcpTransport`` send path.

DETERMINISM: a schedule is a pure function of ``(name, seed)``. Event
parameters that want jitter draw from :meth:`FaultSchedule.rng`, a
``random.Random`` seeded with the STRING key
``paxchaos|<name>|<seed>|<event index>`` (sha512 string seeding,
PYTHONHASHSEED-proof -- the same contract the geo layer and
``FsyncStallStorage`` already enforce). :meth:`FaultSchedule.digest`
is a sha256 over the canonical event list; the sim golden pins it and
the deployed twin records it next to its SLO row, so "both worlds ran
the same schedule" is a checkable equality, not a comment.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
from typing import Iterable, Optional

#: The closed fault vocabulary. Backends must implement every kind
#: (``do_<kind>``); an unknown kind fails schedule construction, not a
#: run half-way through.
KINDS = (
    "crash_role",        # target: role label ("leader_0", sim: address)
    "restart_role",      # relaunch target verbatim (WAL roles recover)
    "crash_zone",        # target: zone index as str ("0")
    "restart_zone",      # relaunch a killed zone (acceptors from WAL)
    "pause",             # SIGSTOP twin: target stops making progress
    "resume",            # SIGCONT: target runs again
    "fsync_stall",       # arm FsyncStallStorage on target acceptor
    "partition",         # params: region_a, region_b (both directions)
    "heal",              # undo one partition
    "brownout",          # params: zone_a, zone_b, extra_s -- ADD this
                         # many seconds of one-way latency to the link
                         # (0 restores). Sim maps it onto the
                         # topology's multiplicative degrade; deployed
                         # injects it flat at the send path -- SAME
                         # physical meaning in both worlds.
    "heal_all",          # heal every partition/brownout
    "repair",            # protocol-level repair (CRAQ chain re-link)
)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One fault at ``t_s`` seconds after the schedule's start.

    ``params`` is a tuple of ``(key, value)`` pairs (sorted by key at
    construction) so events stay hashable and the digest is canonical.
    """

    t_s: float
    kind: str
    target: str = ""
    params: tuple = ()

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"known: {KINDS}")
        object.__setattr__(self, "params",
                           tuple(sorted(self.params)))

    def get(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default

    def canonical(self) -> str:
        params = ",".join(f"{k}={v!r}" for k, v in self.params)
        return f"{self.t_s:.6f}|{self.kind}|{self.target}|{params}"


class FaultSchedule:
    """An ordered, immutable fault plan. Build with :meth:`add` (which
    returns self for chaining) then treat as frozen: backends iterate
    ``events``; :meth:`digest` identifies the plan."""

    def __init__(self, name: str, seed: int = 0,
                 events: Optional[Iterable[FaultEvent]] = None):
        self.name = name
        self.seed = seed
        self.events: tuple[FaultEvent, ...] = tuple(
            sorted(events or (), key=lambda e: e.t_s))

    def add(self, t_s: float, kind: str, target: str = "",
            **params) -> "FaultSchedule":
        event = FaultEvent(t_s=t_s, kind=kind, target=target,
                           params=tuple(params.items()))
        self.events = tuple(sorted(self.events + (event,),
                                   key=lambda e: e.t_s))
        return self

    def rng(self, event_index: int) -> random.Random:
        """String-seeded per-event RNG for parameter jitter (sha512
        seeding -- deterministic across processes and platforms)."""
        return random.Random(
            f"paxchaos|{self.name}|{self.seed}|{event_index}")

    def canonical(self) -> str:
        head = f"paxchaos-schedule|{self.name}|{self.seed}\n"
        return head + "\n".join(e.canonical() for e in self.events)

    def digest(self) -> str:
        """sha256 over the canonical event list -- the cross-world
        identity the twin rows record and the golden test pins."""
        return hashlib.sha256(self.canonical().encode()).hexdigest()

    def launch_events(self) -> list:
        """Events at t == 0 that deployed backends must apply BEFORE
        role launch (fsync-stall arming crosses the process boundary
        via CLI flags; it cannot be injected into a live role)."""
        return [e for e in self.events
                if e.t_s == 0.0 and e.kind == "fsync_stall"]

    def __iter__(self):
        return iter(self.events)

    def __len__(self) -> int:
        return len(self.events)


class ScheduleRunner:
    """Replays one schedule against one backend. The caller owns the
    clock: ``poll(now)`` fires everything due at or before ``now``
    (sim: the transport's virtual clock between driver ticks;
    deployed: ``time.monotonic() - t0`` from the chaos thread), so the
    runner itself contains no time source and stays world-neutral."""

    def __init__(self, schedule: FaultSchedule, backend, t0: float = 0.0):
        self.schedule = schedule
        self.backend = backend
        self.t0 = t0
        self._next = 0
        #: (fire_time, event) log -- the twin rows record it.
        self.fired: list = []

    def next_time(self) -> Optional[float]:
        if self._next >= len(self.schedule.events):
            return None
        return self.t0 + self.schedule.events[self._next].t_s

    def poll(self, now: float) -> int:
        """Fire every event due at or before ``now``; returns how many
        fired."""
        fired = 0
        events = self.schedule.events
        while self._next < len(events) \
                and self.t0 + events[self._next].t_s <= now + 1e-9:
            event = events[self._next]
            self._next += 1
            getattr(self.backend, f"do_{event.kind}")(event)
            self.fired.append((now, event))
            fired += 1
        return fired

    def done(self) -> bool:
        return self._next >= len(self.schedule.events)

    def drive(self, driver, t_end: float) -> None:
        """Sim-side exact-time replay: advance a GeoOverloadDriver to
        each event's virtual instant, fire it, and continue to
        ``t_end`` -- the schedule lands at the same virtual times the
        hand-rolled scenario code used to pick, so per-seed delivery
        histories stay byte-reproducible."""
        while True:
            t = self.next_time()
            if t is None or t >= t_end - 1e-9:
                break
            if t > driver.now:
                driver.run_for(t - driver.now)
            self.poll(driver.now)
        if t_end > driver.now:
            driver.run_for(t_end - driver.now)


# --- the twin schedules ------------------------------------------------------
#
# The matrix scenarios and their deployed twins build their plans HERE
# -- one builder, two worlds -- so the only thing a world contributes
# is its backend and its clock.


def zone_outage_schedule(*, t_kill: float, dwell_s: float,
                         zone: int = 0, seed: int = 0) -> FaultSchedule:
    """SIGKILL a whole zone at ``t_kill``, relaunch it ``dwell_s``
    later (acceptors recover from their WALs, leader/replica come back
    amnesiac) -- the ``zone_outage_peak`` fault plan."""
    return (FaultSchedule("zone_outage", seed=seed)
            .add(t_kill, "crash_zone", str(zone))
            .add(t_kill + dwell_s, "restart_zone", str(zone)))


def ingest_handoff_schedule(*, t_kill: float, dwell_s: float,
                            shard: int = 1,
                            seed: int = 0) -> FaultSchedule:
    """SIGKILL one ingest-batcher shard mid-descriptor-handoff (the
    batcher holds staged commands and un-credited IngestRuns when the
    signal lands), relaunch it ``dwell_s`` later -- the paxfan
    failover plan: the dead shard's ring keys fail over to its
    clockwise survivors on the clients' resend timeout, every other
    key stays pinned, and the cost must be RETRIES, never acked
    loss."""
    return (FaultSchedule("ingest_handoff", seed=seed)
            .add(t_kill, "crash_zone", str(shard))
            .add(t_kill + dwell_s, "restart_zone", str(shard)))


def fsync_stall_schedule(*, window_s: float = 0.15,
                         zone: int = 0,
                         periods: tuple = ((0, 0.8), (1, 2.4)),
                         seed: int = 0) -> FaultSchedule:
    """Arm deterministic PERIODIC-WINDOW fsync stalls on two of
    ``zone``'s acceptors (armed at t=0 -- storage wrapping happens
    before traffic): each target's disk is slow for the first
    ``window_s`` of every period (the background-flush shape from
    "Paxos in the Cloud"). The periods are chosen so acceptor 0
    stalls often but usually ALONE (the row quorum masks it) while
    every one of acceptor 1's windows OVERLAPS one of acceptor 0's
    (2.4 is a multiple of 0.8) -- only those commits reach the client
    tail. Windows anchor at clock zero (virtual clock in the sim, the
    shared host wall clock deployed), so the overlap alignment holds
    in BOTH worlds -- a sync-count cadence drifts apart deployed the
    moment one blocking stall compresses the stalled role's backlog
    into a single drain."""
    schedule = FaultSchedule("fsync_stalls", seed=seed)
    for member, period_s in periods:
        schedule.add(0.0, "fsync_stall", f"{zone}:{member}",
                     period_s=period_s, window_s=window_s)
    return schedule


def craq_chain_kill_schedule(*, t_kill: float, node: int,
                             reconfigure_after_s: float,
                             seed: int = 0) -> FaultSchedule:
    """Kill chain node ``node`` mid-run, then (after a detection
    dwell) re-link the chain around it -- the plan that ends the craq
    row's chaos exemption. The re-link itself is protocol machinery
    (``ChainReconfigure`` with the dirty-version handoff); the
    backend's ``do_repair`` fires it, so both worlds kill and re-link
    on the same plan."""
    return (FaultSchedule("craq_chain_kill", seed=seed)
            .add(t_kill, "crash_role", f"chain_node_{node}")
            .add(t_kill + reconfigure_after_s, "repair",
                 f"chain:{node}"))
